"""DASer: the light-node data-availability sampling daemon.

The client half of the DAS plane (celestia-node `das/daser.go` analog).
A DASer holds nothing but a genesis-rooted light client (chain/light.py)
and a checkpoint file, yet ends every sweep with a quantified availability
claim for each header it follows:

- **header following**: commit certificates are fetched per height and
  verified through the LightClient (>2/3 of the trusted set; condemned
  data roots refused), so every data root the sampler trusts was certified
  — the sampler never takes the serving node's word for what it committed.
- **catch-up scheduling**: pending heights are split into jobs and worked
  by a bounded pool of parallel workers (celestia-node's coordinator +
  catch-up workers), so a node that was down for a thousand blocks
  backfills at worker-pool parallelism while the head keeps advancing.
  A multi-height job samples as a WINDOW (serving plane, FORMATS §17.1):
  one batched /das/headers fetch + one grouped /das/samples round-trip
  cover the whole job, so sampling round-trips per height drop toward
  1/window instead of one request per (height, retry).
- **sampling**: s cells per header, drawn from THIS node's own rng
  (predictable coordinates let a withholder serve exactly what's asked),
  fetched in one batched request — or sliced out of the height's static
  proof pack when the serving peer advertises one (§17.2): chunks are
  sha256-checked against the manifest, every doc still verifies through
  the normal per-sample path, and any shortfall falls back to live
  assembly (a tampered chunk additionally penalizes the peer). Failures
  retry as a subset — immediately on the next peer in rotation
  (``daser.partial_retries``), then with exponential backoff — before
  anything escalates.
- **escalation** (a failed sample after retries): fetch every obtainable
  cell, verify each, and run the 2D repair fixpoint (da/repair.repair_eds)
  over the authenticated shares. Repair completing means the block WAS
  available (flaky peer); `BadEncodingError` means the producer committed
  a non-codeword — the DASer then assembles a bad-encoding fraud proof
  from orthogonal-axis cell proofs (served by das/server.py `axis=col`),
  verifies it via the light client (which condemns the data root), writes
  a HALTED checkpoint, and stops following the chain.
- **checkpointing**: progress persists fsync-before-replace
  (das/checkpoint.py); a restarted DASer re-verifies headers (cheap) but
  never re-samples completed heights (the expensive part).

Confidence math (da/sampling.py): each sample independently catches a
square with > 1/4 of extended cells withheld with probability > 1/4, so
s samples give 1-(3/4)^s; a fleet of m independent samplers compounds to
1-(3/4)^(m*s). docs/DESIGN.md "The DAS plane" has the derivation.
"""

from __future__ import annotations

import base64
import dataclasses
import queue as queue_mod
import threading

import numpy as np

from celestia_app_tpu import appconsts
from celestia_app_tpu import obs
from celestia_app_tpu.chain import light as light_mod
from celestia_app_tpu.da import codec as dacodec
from celestia_app_tpu.da import fraud, repair, sampling
from celestia_app_tpu.da.dah import DataAvailabilityHeader
from celestia_app_tpu.das.checkpoint import Checkpoint, CheckpointStore
from celestia_app_tpu.net.transport import PeerClient, TransportConfig
from celestia_app_tpu.utils import nmt_host, telemetry

log = obs.get_logger("das.daser")


class PeerError(OSError):
    """Every peer failed (or refused) a request after all retries."""


@dataclasses.dataclass
class DASerConfig:
    samples_per_header: int = 16  # s: confidence 1-(3/4)^s ≈ 0.99 at 16
    workers: int = 3  # parallel catch-up workers (bounded in-flight)
    job_size: int = 8  # heights per catch-up job — AND the multi-height
    # sampling window: a whole job goes out as one batched
    # POST /das/samples {groups} round-trip (serving plane, §17.1)
    retries: int = 3  # per-request peer-rotation rounds
    backoff: float = 0.05  # base backoff seconds (doubles per round)
    request_timeout: float = 5.0
    poll_interval: float = 0.25  # head-follow pause in run_background
    # prefer static proof-pack chunks when a serving peer advertises
    # them on /das/header (§17.2); verified chunks carry the same docs
    # as live assembly, a tampered chunk penalizes the peer and falls
    # back to live /das/samples. No-op against pack-less peers.
    prefer_packs: bool = True
    # keep only the newest N per-height reports (0 = unbounded). The
    # checkpoint, not `reports`, is the durable record; a long-horizon
    # fleet (1000+ samplers over thousands of virtual blocks in one
    # process) bounds this so memory stays O(fleet), not O(fleet*chain).
    report_keep: int = 0


class PeerSet:
    """Round-robin rotation over the sampler's peer URLs ON TOP of the
    shared hardened transport (net/transport.py): each retry round tries
    EVERY peer once, so a single withholding/flaky peer never decides
    availability while an honest peer holds the data. The per-peer
    backoff/breaker/health machinery lives in the PeerClient — one
    implementation shared with the reactor's gossip — while this class
    keeps the DASer's rotation semantics and its `daser.requests` /
    `daser.peer_errors` / `daser.retry_rounds` counters."""

    def __init__(self, urls: list[str], timeout: float = 5.0,
                 retries: int = 3, backoff: float = 0.05,
                 client: PeerClient | None = None, clock=None):
        if not urls:
            raise ValueError("PeerSet needs at least one peer URL")
        from celestia_app_tpu.utils import clock as clock_mod

        self.urls = [u.rstrip("/") for u in urls]
        self.retries = retries
        self.backoff = backoff
        # retry-round backoff time source: SystemClock by default; the
        # scenario plane injects its VirtualClock so rotation backoffs
        # cost virtual seconds (utils/clock.py)
        self.clock = clock if clock is not None else clock_mod.SYSTEM
        # one transport attempt per (peer, round): the ROTATION is this
        # class's retry loop; a dead peer trips its breaker here exactly
        # as it would under the reactor, and subsequent rounds skip it at
        # BreakerOpen speed instead of paying connect timeouts
        self.client = client or PeerClient(
            TransportConfig(timeout=timeout, retries=1),
            name="daser",
        )
        self._i = 0
        self._lock = threading.Lock()

    def _order(self) -> list[str]:
        with self._lock:
            start = self._i
            self._i = (self._i + 1) % len(self.urls)
        return self.urls[start:] + self.urls[:start]

    def request(self, path: str, payload: dict | None = None,
                raw: bool = False):
        """GET (payload None) or POST `path`, rotating peers with
        exponential backoff between rounds; raises PeerError when every
        peer failed every round. HTTP error bodies ({"error": ...}) are
        treated as refusals and retried on the next peer."""
        return self.request_from(path, payload, raw=raw)[1]

    def request_from(self, path: str, payload: dict | None = None,
                     raw: bool = False):
        """`request`, but returns ``(peer_url, body)`` — callers that
        verify content hashes (pack chunk fetches) need to know WHICH
        peer served the bytes so a mismatch can be penalized on the
        shared health score (net.penalize)."""
        last = "no peers"
        delay = self.backoff
        for attempt in range(self.retries):
            for url in self._order():
                try:
                    telemetry.incr("daser.requests")
                    return url, self.client.request(url, path, payload,
                                                    raw=raw)
                except (OSError, ValueError) as e:
                    telemetry.incr("daser.peer_errors")
                    last = f"{url}{path}: {type(e).__name__}: {e}"
            if attempt + 1 < self.retries:
                telemetry.incr("daser.retry_rounds")
                self.clock.sleep(delay)
                delay *= 2
        raise PeerError(f"all peers failed: {last}")

    def penalize(self, url: str, reason: str) -> None:
        """Content-level failure report (e.g. a pack chunk whose sha256
        mismatched its manifest): feeds the shared transport's per-peer
        health score so a corrupt-serving peer is eventually
        breaker-skipped (net/transport.PeerClient.penalize)."""
        self.client.penalize(url, reason)

    def request_pinned(self, url: str, path: str,
                       payload: dict | None = None, raw: bool = False):
        """One attempt against ONE specific peer — no rotation. Pack
        chunk fetches use this: a chunk must be fetched from the peer
        whose manifest advertised it (chunk boundaries are per-node
        config), or an honest peer could be penalized for another
        node's advert. Raises OSError/ValueError on failure."""
        telemetry.incr("daser.requests")
        return self.client.request(url, path, payload, raw=raw)


def http_header_source(peers: PeerSet):
    """(height) -> (Header, CommitCertificate) via the node service's
    /ibc/header route (the same certified-header payload the IBC
    verifying client consumes). Returns None while the height is not yet
    certified on any peer."""
    from celestia_app_tpu.chain import consensus

    def fetch(height: int):
        try:
            doc = peers.request("/ibc/header", {"height": height})
        except PeerError:
            return None
        try:
            return (consensus.header_from_json(doc["header"]),
                    consensus.cert_from_json(doc["cert"]))
        except (KeyError, ValueError, TypeError):
            return None

    return fetch


class DASer:
    """One light node's sampling daemon. Drive it with `sync()` (one full
    sweep: follow head, catch up, checkpoint) or `run_background()`."""

    def __init__(self, peers, light: light_mod.LightClient,
                 store: CheckpointStore,
                 cfg: DASerConfig | None = None,
                 header_source=None, rng=None, name: str = "daser",
                 clock=None):
        from celestia_app_tpu.utils import clock as clock_mod

        self.cfg = cfg or DASerConfig()
        # sweep/retry/backoff time source (utils/clock.py): SystemClock
        # by default; the scenario plane injects its VirtualClock so one
        # process can run hundreds of samplers over hours of chain time
        self.clock = clock if clock is not None else clock_mod.SYSTEM
        self.peers = peers if isinstance(peers, PeerSet) else PeerSet(
            peers, timeout=self.cfg.request_timeout,
            retries=self.cfg.retries, backoff=self.cfg.backoff,
            clock=self.clock,
        )
        self.light = light
        self.store = store
        self.name = name
        # the durable sampling watermark; workers consult halted, the
        # coordinator folds results into it
        self.cp: Checkpoint = store.load()  # guarded-by: _lock
        self.header_source = header_source or http_header_source(self.peers)
        # the light node's OWN entropy — a withholder that can predict
        # coordinates serves exactly the sampled cells and nothing else
        self.rng = rng if rng is not None else np.random.default_rng()  # lint: disable=det-rng
        # height -> (data_root hex, ods square size), from VERIFIED headers
        self._roots: dict[int, tuple[str, int]] = {}
        # this light node's OWN span plane (obs/spans.py): rows carry the
        # same deterministic per-height trace ids the serving chain uses,
        # so tools/timeline.py merges them into one waterfall
        self.traces = telemetry.TraceTables()
        self.reports: dict[int, dict] = {}  # guarded-by: _lock
        self._lock = threading.Lock()
        # lock-free mirror of cp.halted for the workers' per-height hot
        # path: _fold holds _lock across an fsync'd checkpoint save, and
        # samplers must not queue behind the disk just to poll a flag
        self._halted_evt = threading.Event()
        # consecutive whole-window batch-route failures; >= 2 disables
        # the batched /das/headers + {groups} paths for this DASer (a
        # legacy peer set must not cost every window two rotation-and-
        # backoff exhaustions before the per-height fallback)
        self._batch_failures = 0  # guarded-by: _lock
        if self.cp.halted is not None:
            self._halted_evt.set()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- state -----------------------------------------------------------

    @property
    def halted(self) -> bool:
        with self._lock:
            return self.cp.halted is not None

    def _halt(self, height: int, reason: str, data_root: str) -> None:
        # snapshot under the lock, fsync OUTSIDE it (found by the
        # blocking-under-lock rule): workers polling `halted` must not
        # queue behind a disk flush. A concurrent _fold save racing
        # this write is harmless — both docs are valid checkpoints and
        # the store's atomic replace can only UNDER-claim progress.
        doc = None
        with self._lock:
            if self.cp.halted is None:
                self.cp.halted = {
                    "height": height, "reason": reason,
                    "data_root": data_root,
                }
                self._halted_evt.set()
                doc = self.cp.to_json()
        if doc is not None:
            self.store.save_doc(doc)
        telemetry.incr("daser.halts")

    # -- header following (coordinator; sequential light-client trust) ---

    def _advance_head(self) -> None:
        try:
            head = int(self.peers.request("/das/head")["height"])
        except (PeerError, KeyError, ValueError, TypeError):
            return
        while self.light.trusted.height < head and not self._stop.is_set():
            h = self.light.trusted.height + 1
            got = self.header_source(h)
            if got is None:
                break  # not yet certified anywhere; try next sweep
            header, cert = got
            try:
                self.light.update(header, cert)
            except light_mod.LightClientError as e:
                if "condemned" in str(e):
                    self._halt(h, "condemned-root",
                               header.data_hash.hex())
                # valset changes need operator-supplied candidate sets;
                # either way this sweep stops following here
                break
            self._roots[h] = (header.data_hash.hex(), header.square_size)
            with self._lock:
                self.cp.network_head = max(self.cp.network_head, h)

    # -- sampling workers ------------------------------------------------

    @staticmethod
    def _parse_header_doc(doc: dict, root_hex: str, square_size: int):
        """(codec, commitments, pack-advert|None) from a served
        commitments doc: the doc names its scheme (absent ⇒ rs2d-nmt)
        and the codec parses AND verifies it against the certified root
        — bounds/shapes first, binding second, all on untrusted input
        (da/codec.py). The optional "pack" member advertises the
        height's static proof pack (§17.2); it is shape-checked here and
        content-checked chunk by chunk at fetch time."""
        codec = dacodec.get(doc.get("scheme", dacodec.RS2D_NAME))
        commitments = codec.commitments_from_doc(doc, root_hex,
                                                 square_size)
        pack = doc.get("pack")
        if not (isinstance(pack, dict)
                and isinstance(pack.get("chunk_hashes"), list)
                and isinstance(pack.get("chunk_cells"), int)
                and pack.get("chunk_cells", 0) > 0
                and pack.get("data_root") == root_hex):
            pack = None
        return codec, commitments, pack

    def _fetch_commitments(self, height: int, root_hex: str,
                           square_size: int):
        """One height's parsed header doc (the per-height fallback of
        the batched /das/headers window fetch). The pack advert (if
        any) is stamped with the peer that served it — chunk fetches
        pin to that peer."""
        telemetry.incr("daser.sampling_round_trips")
        url, doc = self.peers.request_from(
            f"/das/header?height={height}")
        codec, commitments, pack = self._parse_header_doc(
            doc, root_hex, square_size)
        if pack is not None:
            pack = {**pack, "peer": url}
        return codec, commitments, pack

    def _batch_routes_ok(self) -> bool:
        with self._lock:
            return self._batch_failures < 2

    def _note_batch(self, ok: bool) -> None:
        """Memoize whether the peer set answers the batched window
        routes: a legacy peer set would otherwise cost every window two
        full rotation-with-backoff exhaustions before falling back."""
        with self._lock:
            self._batch_failures = 0 if ok else self._batch_failures + 1

    def _fetch_headers_batch(self, job) -> tuple[str | None,
                                                 dict[int, dict]]:
        """(serving peer, height -> raw header doc) for a window, in
        ONE round-trip (POST /das/headers). Heights the peer could not
        serve (or a peer set without the batched route at all) simply
        come back absent — callers fall back to the per-height fetch."""
        heights = [h for h, _root, _size in job]
        if not self._batch_routes_ok():
            return None, {}
        try:
            telemetry.incr("daser.sampling_round_trips")
            url, out = self.peers.request_from("/das/headers",
                                               {"heights": heights})
            docs = out["headers"]
        except (PeerError, KeyError, TypeError, ValueError):
            self._note_batch(False)
            return None, {}
        self._note_batch(True)
        got: dict[int, dict] = {}
        for doc in docs if isinstance(docs, list) else []:
            try:
                h = int(doc["height"])
            except (KeyError, TypeError, ValueError):
                continue
            if h in heights and "error" not in doc:
                got[h] = doc
        return url, got

    @staticmethod
    def _decode_sample(s: dict) -> tuple[bytes, nmt_host.NmtRangeProof]:
        return (
            base64.b64decode(s["share"]),
            nmt_host.NmtRangeProof(
                start=int(s["proof"]["start"]),
                end=int(s["proof"]["end"]),
                total=int(s["proof"]["total"]),
                nodes=[base64.b64decode(n) for n in s["proof"]["nodes"]],
            ),
        )

    def _fetch_cells(self, height: int, cells, axis: str = "row") -> list[dict]:
        """Batched fetch; whole-request failures already rotate peers in
        PeerSet. Returns the per-cell sample docs (error members kept).
        The span context rides the request (X-Celestia-Trace), so the
        serving node's das.serve_sample span links back here."""
        with obs.span("das.fetch_cells", traces=self.traces,
                      height=height, cells=len(cells), axis=axis):
            telemetry.incr("daser.sampling_round_trips")
            out = self.peers.request(
                "/das/samples",
                {"height": height, "cells": [list(c) for c in cells],
                 "axis": axis},
            )
        return out["samples"]

    def _fetch_groups(self, draws: dict[int, list]) -> dict[int, dict]:
        """height -> single-height-shaped response for a window of
        heights, in ONE round-trip (POST /das/samples {groups}): the
        rewrite that takes catch-up from one request per (height, retry)
        to ~1/window. Raises PeerError when no peer serves the window."""
        if not self._batch_routes_ok():
            return {}
        groups = [{"height": h, "cells": [list(c) for c in cells]}
                  for h, cells in sorted(draws.items())]
        with obs.span("das.fetch_window", traces=self.traces,
                      heights=len(groups),
                      cells=sum(len(g["cells"]) for g in groups)):
            telemetry.incr("daser.sampling_round_trips")
            try:
                out = self.peers.request("/das/samples",
                                         {"groups": groups})
            except PeerError:
                self._note_batch(False)
                raise
        got: dict[int, dict] = {}
        for resp in out.get("groups") or []:
            try:
                got[int(resp["height"])] = resp
            except (KeyError, TypeError, ValueError):
                continue
        if got:
            self._note_batch(True)
        return got

    def _verify_docs(self, codec, commitments,
                     docs: list[dict]) -> tuple[dict, list]:
        """Scheme-dispatched doc verification: the rs2d inline DAH path
        or the codec interface — one call site for every fetch flavor
        (live batch, window group, pack chunk)."""
        if codec.name == dacodec.RS2D_NAME:
            return self._verify_cells(commitments, docs)
        return self._verify_cells_codec(codec, commitments, docs)

    # -- proof packs (client side) ----------------------------------------

    def _fetch_verified_chunk(self, height: int, ci: int,
                              want_hash: str,
                              peer: str) -> list[dict] | None:
        """One sha-verified pack chunk, PINNED to the peer whose header
        doc advertised the manifest (chunk boundaries/hashes are
        per-node config, so fetching from a rotated peer could penalize
        an honest node for another's advert). Returns the decoded doc
        list, or None on any shortfall; a hash-mismatched or
        undecodable body penalizes the advertising peer — corrupt (or
        lying) static serving must never decide availability."""
        import hashlib

        from celestia_app_tpu.das import packs as packs_mod

        try:
            telemetry.incr("daser.sampling_round_trips")
            data = self.peers.request_pinned(
                peer, f"/das/pack/chunk?height={height}&index={ci}",
                raw=True,
            )
        except (OSError, ValueError):
            telemetry.incr("daser.pack_fallbacks")
            return None
        if hashlib.sha256(data).hexdigest() != want_hash:
            telemetry.incr("daser.pack_chunk_rejected")
            self.peers.penalize(
                peer, f"pack chunk {height}/{ci} hash mismatch")
            return None
        try:
            return packs_mod.decode_chunk(data)
        except ValueError:
            telemetry.incr("daser.pack_chunk_rejected")
            self.peers.penalize(
                peer, f"pack chunk {height}/{ci} undecodable")
            return None

    def _fetch_pack_docs(self, height: int, pack: dict, cells,
                         codec, commitments) -> list[dict] | None:
        """The sampled cells' docs out of static pack chunks: map each
        cell to its chunk by sample-space position, fetch the distinct
        chunks (pinned to the advertising peer), verify each chunk's
        sha256 against the advertised manifest, and slice out the cell
        docs. Returns None on ANY shortfall — the caller falls back to
        live assembly. Note the cell docs themselves are verified by
        the normal per-sample path afterwards, so a lying manifest buys
        an adversary nothing."""
        peer = pack.get("peer")
        if peer is None:
            return None
        space = codec.sample_space(commitments)
        index_of = {cell: i for i, cell in enumerate(space)}
        chunk_cells = int(pack["chunk_cells"])
        need: dict[int, list] = {}
        for cell in cells:
            i = index_of.get(tuple(cell))
            if i is None:
                return None
            need.setdefault(i // chunk_cells, []).append((cell, i))
        hashes = pack["chunk_hashes"]
        docs: list[dict] = []
        for ci in sorted(need):
            if not 0 <= ci < len(hashes):
                return None
            chunk_docs = self._fetch_verified_chunk(height, ci,
                                                    hashes[ci], peer)
            if chunk_docs is None:
                return None
            for _cell, i in need[ci]:
                off = i - ci * chunk_cells
                if not 0 <= off < len(chunk_docs):
                    telemetry.incr("daser.pack_fallbacks")
                    return None
                docs.append(chunk_docs[off])
        telemetry.incr("daser.pack_samples", len(docs))
        return docs

    def _verify_cells(self, dah: DataAvailabilityHeader,
                      docs: list[dict]) -> tuple[dict, list]:
        """Split served docs into {coord: (share, proof)} verified against
        the DAH and the list of failed coords."""
        good: dict[tuple[int, int], tuple] = {}
        failed: list[tuple[int, int]] = []
        for s in docs:
            coord = (int(s["row"]), int(s["col"]))
            if "error" in s:
                failed.append(coord)
                continue
            try:
                share, proof = self._decode_sample(s)
                ok = sampling.verify_sample(dah, coord[0], coord[1],
                                            share, proof)
            except (KeyError, ValueError, TypeError):
                ok = False
            if ok:
                good[coord] = (share, proof)
            else:
                failed.append(coord)
        return good, failed

    def _sample_height(self, height: int, root_hex: str,
                       square_size: int, rng=None) -> dict:
        """One height end-to-end; never raises. Returns the report dict
        ({"status": "sampled"|"recovered"|"fraud"|"unavailable"|"error"}).
        `rng` is the calling worker's own generator (numpy Generators are
        not thread-safe; sharing one across workers would correlate the
        draws the confidence bound assumes independent)."""
        # the light-node side of the height's trace: same deterministic
        # id the chain stamps, derived locally from (chain_id, height) —
        # the DAS sample round-trip joins the block's waterfall
        with obs.span(
            "das.sample_height", traces=self.traces,
            trace_id=obs.trace_id_for(self.light.chain_id, height),
            height=height, node=self.name,
        ) as sp:
            out = self._sample_height_inner(height, root_hex, square_size,
                                            rng)
            sp.set(status=out.get("status"))
            return out

    def _sample_height_inner(self, height: int, root_hex: str,
                             square_size: int, rng=None) -> dict:
        rng = rng if rng is not None else self.rng
        t0 = telemetry.start_timer()
        try:
            codec, commitments, pack = self._fetch_commitments(
                height, root_hex, square_size)
        except (PeerError, ValueError, KeyError) as e:
            telemetry.incr("daser.header_fetch_failures")
            return {"status": "error", "error": str(e)}
        cells = self._draw(codec, commitments, rng)
        out = self._sample_cells(height, codec, commitments, root_hex,
                                 cells, pack)
        telemetry.measure_since("daser.sample_height", t0)
        return out

    def _draw(self, codec, commitments, rng) -> list[tuple[int, int]]:
        """s cells from THIS sampler's own rng — uniform over the
        scheme's sample space (the rs2d draw stays the exact legacy pair
        sequence, so seeded samplers reproduce pre-window coordinates)."""
        s = self.cfg.samples_per_header
        if codec.name == dacodec.RS2D_NAME:
            width = len(commitments.row_roots)
            return [
                (int(rng.integers(0, width)), int(rng.integers(0, width)))
                for _ in range(s)
            ]
        space = codec.sample_space(commitments)
        return [space[int(rng.integers(0, len(space)))]
                for _ in range(s)]

    def _sample_cells(self, height: int, codec, commitments,
                      root_hex: str, cells, pack,
                      prefetched: list[dict] | None = None) -> dict:
        """Verify + retry + escalate one height's drawn cells. The docs
        come from (in preference order) a window group fetch
        (``prefetched``), the height's static proof pack, or a live
        batched fetch — all three verify through the same per-sample
        path, so the source never weakens the availability claim."""
        s = len(cells)
        docs = prefetched
        if docs is None and pack is not None and self.cfg.prefer_packs:
            docs = self._fetch_pack_docs(height, pack, cells, codec,
                                         commitments)
        if docs is None:
            try:
                docs = self._fetch_cells(height, cells)
            except PeerError as e:
                return {"status": "error", "error": str(e)}
        good, failed = self._verify_docs(codec, commitments, docs)
        good, failed = self._retry_failed(height, codec, commitments,
                                          good, failed)
        telemetry.incr("daser.samples_verified", len(good))
        report = {
            "samples": s,
            "verified": len(good),
            "failed": sorted(set(failed)),
        }
        if codec.name == dacodec.RS2D_NAME:
            report["confidence"] = sampling.withholding_catch_confidence(s)
        else:
            report["confidence"] = codec.confidence(s)
            report["scheme"] = codec.name
        if not failed:
            telemetry.incr("daser.headers_sampled")
            return {**report, "status": "sampled"}
        telemetry.incr("daser.samples_failed", len(set(failed)))
        if codec.name == dacodec.RS2D_NAME:
            return {**report,
                    **self._escalate(height, commitments, root_hex,
                                     pack=pack)}
        return {**report,
                **self._escalate_codec(height, codec, commitments,
                                       root_hex, pack=pack)}

    def _retry_failed(self, height: int, codec, commitments, good: dict,
                      failed: list) -> tuple[dict, list]:
        """Per-cell retries: a refused/garbled cell may be served by the
        next peer in rotation (PeerSet advances its starting peer per
        request). The FIRST retry of a partially-failed batch goes out
        immediately — one flaky cell must not cost the whole batch a
        backoff sleep (counted ``daser.partial_retries``); deterministic
        refusals then exhaust the backed-off rounds and escalate."""
        if failed:
            telemetry.incr("daser.partial_retries")
            try:
                docs = self._fetch_cells(height, failed)
                recovered, failed = self._verify_docs(codec, commitments,
                                                      docs)
                good.update(recovered)
            except PeerError:
                pass
        delay = self.cfg.backoff
        for _ in range(self.cfg.retries):
            if not failed:
                break
            self.clock.sleep(delay)
            delay *= 2
            try:
                docs = self._fetch_cells(height, failed)
            except PeerError:
                continue
            recovered, failed = self._verify_docs(codec, commitments,
                                                  docs)
            good.update(recovered)
        return good, failed

    # -- the catch-up window (serving plane) -----------------------------

    def _sample_window(self, job, rng) -> dict[int, dict]:
        """One catch-up job sampled as a WINDOW: one batched header
        fetch plus one multi-height grouped sample fetch cover every
        height in the job, so sampling round-trips per height drop
        toward 1/window (was one request per (height, retry)). Each
        height still verifies, retries, and escalates independently —
        a bad height in a window costs only its own follow-ups."""
        reports: dict[int, dict] = {}
        ctx: dict[int, tuple] = {}
        header_peer, header_docs = self._fetch_headers_batch(job)
        for h, root_hex, size in job:
            doc = header_docs.get(h)
            try:
                if doc is not None:
                    codec, commitments, pack = self._parse_header_doc(
                        doc, root_hex, size)
                    if pack is not None:
                        # chunk fetches pin to the advertising peer
                        pack = {**pack, "peer": header_peer}
                    ctx[h] = (codec, commitments, pack)
                else:
                    ctx[h] = self._fetch_commitments(h, root_hex, size)
            except (PeerError, ValueError, KeyError) as e:
                telemetry.incr("daser.header_fetch_failures")
                reports[h] = {"status": "error", "error": str(e)}
        draws = {h: self._draw(ctx[h][0], ctx[h][1], rng)
                 for h, _root, _size in job if h in ctx}
        groups: dict[int, dict] = {}
        if draws:
            try:
                groups = self._fetch_groups(draws)
            except PeerError:
                # no peer served the window: per-height fetches below
                # (pack or live) still get their chance
                groups = {}
        for h, root_hex, _size in job:
            if h in reports or self._stop.is_set() \
                    or self._halted_evt.is_set():
                continue
            codec, commitments, pack = ctx[h]
            resp = groups.get(h)
            prefetched = (resp.get("samples")
                          if resp is not None and "error" not in resp
                          else None)
            with obs.span(
                "das.sample_height", traces=self.traces,
                trace_id=obs.trace_id_for(self.light.chain_id, h),
                height=h, node=self.name, window=len(job),
            ) as sp:
                t0 = telemetry.start_timer()
                rep = self._sample_cells(h, codec, commitments, root_hex,
                                         draws[h], pack,
                                         prefetched=prefetched)
                telemetry.measure_since("daser.sample_height", t0)
                sp.set(status=rep.get("status"))
            reports[h] = rep
        return reports

    # -- non-default schemes: codec-interface sampling + escalation ------

    def _verify_cells_codec(self, codec, commitments,
                            docs: list[dict]) -> tuple[dict, list]:
        """Split served docs into {cell: (payload, doc)} verified via
        the codec and the list of failed cells. The full doc rides along
        because a fraud proof's members carry their served proofs."""
        good: dict[tuple[int, int], tuple] = {}
        failed: list[tuple[int, int]] = []
        for s in docs:
            coord = (int(s["row"]), int(s["col"]))
            if "error" in s:
                failed.append(coord)
                continue
            got = codec.verify_sample(commitments, s)
            if got is not None and got[0] == coord:
                good[coord] = (got[1], s)
            else:
                failed.append(coord)
        return good, failed

    def _escalate_codec(self, height: int, codec, commitments,
                        root_hex: str, pack: dict | None = None) -> dict:
        """Codec-interface escalation: fetch every obtainable base
        symbol in bounded batches, run the scheme's repair (the peeling
        decoder for cmt-ldpc), and either clear the block, condemn it
        with a verified fraud proof, or record it unavailable. Scheme-
        generic: the only detection type caught is the interface's
        BadEncodingDetected base, and proof assembly goes through the
        codec's fraud_cells/fraud_proof_from_members hooks."""
        telemetry.incr("daser.escalations")
        space = codec.sample_space(commitments)
        chunk = 256  # bounded request batches (the rs2d row discipline)
        batches = [space[start:start + chunk]
                   for start in range(0, len(space), chunk)]
        docs_map: dict[tuple[int, int], tuple] = {}
        for docs in self._fetch_all_docs(height, pack, batches):
            good, _failed = self._verify_cells_codec(codec, commitments,
                                                     docs)
            docs_map.update(good)
        if not docs_map:
            return {"status": "unavailable",
                    "error": "no peer served any reconstruction cells"}
        samples = {cell: payload
                   for cell, (payload, _doc) in docs_map.items()}
        try:
            t_rep = telemetry.start_timer()
            try:
                codec.repair(commitments, samples)
            finally:
                telemetry.measure_since("daser.repair", t_rep)
        except dacodec.BadEncodingDetected as e:
            proof = self._build_codec_fraud(height, codec, commitments,
                                            docs_map, e.location)
            if proof is not None and self.light.submit_fraud_proof(
                    commitments, proof):
                telemetry.incr("daser.befp_verified")
                self._halt(height, "bad-encoding", root_hex)
                return {"status": "fraud",
                        "location": list(e.location)}
            telemetry.incr("daser.befp_failed")
            return {"status": "unavailable",
                    "error": f"bad encoding at {e.location} but fraud "
                             "proof could not be assembled"}
        except ValueError as e:
            telemetry.incr("daser.unavailable")
            return {"status": "unavailable", "error": str(e)}
        telemetry.incr("daser.recovered")
        return {"status": "recovered"}

    def _build_codec_fraud(self, height: int, codec, commitments,
                           docs_map: dict, location):
        """Assemble the scheme's compact fraud proof from served symbol
        docs (each already carries its own inclusion proof); any member
        missing from the escalation sweep is fetched by its cell."""
        try:
            cells = codec.fraud_cells(commitments, location)
        except NotImplementedError:
            return None
        carried = []
        for cell in cells:
            got = docs_map.get(cell)
            if got is None:
                try:
                    docs = self._fetch_cells(height, [cell])
                except PeerError:
                    return None
                good, _failed = self._verify_cells_codec(
                    codec, commitments, docs)
                got = good.get(cell)
            if got is None:
                return None
            payload, doc = got
            carried.append((cell, payload, doc))
        return codec.fraud_proof_from_members(commitments, location,
                                              carried)

    def _fetch_all_docs(self, height: int, pack: dict | None,
                        batches: list[list]):
        """Escalation's full fetch, yielding doc lists: every pack chunk
        when the height advertises one (static bytes, each sha-verified
        and pinned to the advertising peer — at k=128 this replaces 256
        assembled row requests with 256 file reads), else the bounded
        live batches. Any pack shortfall falls back to the live batches
        wholesale."""
        peer = pack.get("peer") if pack is not None else None
        if peer is not None and self.cfg.prefer_packs:
            all_docs: list[list[dict]] = []
            for ci, want in enumerate(pack["chunk_hashes"]):
                chunk_docs = self._fetch_verified_chunk(height, ci,
                                                        want, peer)
                if chunk_docs is None:
                    all_docs = []
                    break
                all_docs.append(chunk_docs)
            if all_docs:
                telemetry.incr(
                    "daser.pack_samples",
                    sum(len(d) for d in all_docs))
                yield from all_docs
                return
        for batch in batches:
            try:
                yield self._fetch_cells(height, batch)
            except PeerError:
                continue

    # -- escalation: repair -> fraud proof -------------------------------

    def _escalate(self, height: int, dah: DataAvailabilityHeader,
                  root_hex: str, pack: dict | None = None) -> dict:
        """A sample failed after retries: fetch everything obtainable,
        reconstruct, and either clear the block (it WAS available),
        condemn it with a verified BEFP, or record it unavailable."""
        telemetry.incr("daser.escalations")
        width = len(dah.row_roots)
        # row-sized batches, not one square-sized request: a k=128 square
        # is 64k cells (~100 MB of b64) — a single request would blow the
        # peer timeout and misreport an available block as unavailable.
        # A failed row batch just leaves its cells absent; the crossword
        # tolerates holes up to the repair threshold.
        docs: list[dict] = []
        for batch_docs in self._fetch_all_docs(
            height, pack,
            [[(r, c) for c in range(width)] for r in range(width)],
        ):
            docs += batch_docs
        if not docs:
            return {"status": "unavailable",
                    "error": "no peer served any reconstruction cells"}
        good, _failed = self._verify_cells(dah, docs)
        symbols = np.zeros((width, width, appconsts.SHARE_SIZE),
                           dtype=np.uint8)
        present = np.zeros((width, width), dtype=bool)
        for (r, c), (share, _proof) in good.items():
            symbols[r, c] = np.frombuffer(share, dtype=np.uint8)
            present[r, c] = True
        try:
            # the batched sweep engine (da/repair.py): per-pattern fused
            # decode matmuls + per-sweep batched root verification; its
            # da.repair.sweep / da.repair.verify_roots spans land in this
            # light node's trace tables and nest under das.sample_height
            t_rep = telemetry.start_timer()
            try:
                repair.repair_eds(symbols, present,
                                  list(dah.row_roots), list(dah.col_roots),
                                  traces=self.traces)
            finally:
                # the fraud/unsolvable outcomes are exactly the repairs
                # worth timing — measure on every path
                telemetry.measure_since("daser.repair", t_rep)
        except repair.BadEncodingError as e:
            befp = self._build_befp(height, dah, e.axis, e.index)
            if befp is not None and self.light.submit_fraud_proof(dah, befp):
                telemetry.incr("daser.befp_verified")
                self._halt(height, "bad-encoding", root_hex)
                return {"status": "fraud", "axis": e.axis,
                        "index": e.index}
            telemetry.incr("daser.befp_failed")
            return {"status": "unavailable",
                    "error": f"bad {e.axis} {e.index} but BEFP "
                             "could not be assembled"}
        except ValueError as e:
            telemetry.incr("daser.unavailable")
            return {"status": "unavailable", "error": str(e)}
        # the crossword completed and every axis root checked out: the
        # data IS recoverable, the failing samples were peer flakiness
        telemetry.incr("daser.recovered")
        return {"status": "recovered"}

    def _build_befp(self, height: int, dah: DataAvailabilityHeader,
                    axis: str, index: int):
        """Assemble a BadEncodingProof for the condemned axis from served
        orthogonal-axis cell proofs: for a bad ROW its cells are proven
        under the COLUMN roots (and vice versa) — the exact ShareWithProof
        members da/fraud.verify_befp checks, no full square needed."""
        width = len(dah.row_roots)
        k = width // 2
        ortho = "col" if axis == "row" else "row"
        cells = [(index, j) if axis == "row" else (j, index)
                 for j in range(width)]
        try:
            docs = self._fetch_cells(height, cells, axis=ortho)
        except PeerError:
            return None
        ortho_roots = dah.col_roots if axis == "row" else dah.row_roots
        shares: list[fraud.ShareWithProof] = []
        for s in docs:
            if "error" in s or len(shares) >= k:
                continue
            r, c = int(s["row"]), int(s["col"])
            j = c if axis == "row" else r
            try:
                share, proof = self._decode_sample(s)
            except (KeyError, ValueError):
                continue
            ns = fraud.leaf_ns(r, c, share, k)
            if (proof.start == index and proof.end == index + 1
                    and proof.verify(ortho_roots[j], [(ns, share)])):
                shares.append(fraud.ShareWithProof(
                    position=j, share=share, proof=proof,
                ))
        if len(shares) < k:
            return None
        return fraud.BadEncodingProof(axis=axis, index=index,
                                      shares=tuple(shares[:k]))

    # -- the sweep -------------------------------------------------------

    def _pending_heights(self) -> list[tuple[int, str, int]]:
        pend = []
        with self._lock:
            for h in range(self.cp.sample_from,
                           self.cp.network_head + 1):
                if h in self._roots:
                    pend.append((h, *self._roots[h]))
            for h in sorted(self.cp.failed):
                if h < self.cp.sample_from and h in self._roots:
                    # retry earlier failures
                    pend.append((h, *self._roots[h]))
        return pend

    def _sweep_job(self, job, rng) -> dict[int, dict]:
        """One catch-up job end to end on the caller's thread — the unit
        both sweep drivers (threaded workers and the continuation's
        steps) execute identically. A multi-height job goes out as one
        WINDOW (batched headers + grouped samples, serving plane §17.1);
        a single-height job walks the per-height path with the stop/halt
        gates the threaded worker always honored."""
        if len(job) > 1:
            return self._sample_window(job, rng)
        reps: dict[int, dict] = {}
        for h, root_hex, size in job:
            if self._stop.is_set() or self._halted_evt.is_set():
                break
            reps[h] = self._sample_height(h, root_hex, size, rng=rng)
        return reps

    def begin_sweep(self) -> "SweepCont":
        """A sweep as an explicit continuation: drive it with
        ``step()`` until False. Scheduler-friendly — a fleet of
        thousands of samplers interleaves one bounded unit of work per
        event instead of pinning an OS thread each (sim/engine.py)."""
        return SweepCont(self)

    def sync(self) -> dict:
        """One full sweep: follow the head through the light client, then
        catch up over every pending height with the bounded worker pool,
        fold results into the checkpoint, and persist it. Returns a
        summary {"head", "sample_from", "sampled", "failed", "halted"}.

        A thin threaded driver over the SweepCont phases: the plan and
        fold steps run here on the caller's thread; the job list is
        drained by the worker pool racing a queue, each worker executing
        the same ``_sweep_job`` unit the continuation steps through
        (pinned equivalent at workers=1 in tests/test_daser_cont.py)."""
        cont = self.begin_sweep()
        cont.step()  # plan: halted gate, head follow, job split, rngs
        if cont.phase == "jobs":
            jobs: queue_mod.Queue = queue_mod.Queue()
            for job in cont.jobs:
                jobs.put(job)

            def worker(rng) -> None:
                while not self._stop.is_set() \
                        and not self._halted_evt.is_set():
                    try:
                        job = jobs.get_nowait()
                    except queue_mod.Empty:
                        return
                    reps = self._sweep_job(job, rng)
                    with self._lock:
                        cont.results.update(reps)
                        self.reports.update(reps)

            threads = [
                threading.Thread(target=worker, args=(child,), daemon=True)
                for child in cont.rngs
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            cont.phase = "fold"
        while cont.step():
            pass
        return cont.summary

    def _fold(self, results: dict[int, dict]) -> None:
        """Checkpoint bookkeeping: completed heights clear from the failed
        map; incomplete ones record an attempt; the sample_from watermark
        advances over every height that has a durable disposition."""
        done_now = set()
        telemetry.incr("daser.heights_swept", len(results))
        with self._lock:
            for h, rep in results.items():
                if rep["status"] in ("sampled", "recovered"):
                    self.cp.failed.pop(h, None)
                    done_now.add(h)
                elif rep["status"] in ("unavailable", "error"):
                    self.cp.failed[h] = self.cp.failed.get(h, 0) + 1
            while self.cp.sample_from <= self.cp.network_head and (
                    self.cp.sample_from in done_now
                    or self.cp.sample_from in self.cp.failed):
                self.cp.sample_from += 1
            # bound the verified-root map: everything durably sampled
            # and not awaiting a failed-height retry can go (headers
            # re-verify cheaply)
            floor = min(
                [self.cp.sample_from] + sorted(self.cp.failed)[:1])
            for h in [h for h in self._roots if h < floor]:
                del self._roots[h]
            keep = self.cfg.report_keep
            if keep > 0 and len(self.reports) > keep:
                # oldest-height reports go first; anything below the
                # watermark is already durably dispositioned in the
                # checkpoint and never re-swept
                for h in sorted(self.reports)[:len(self.reports) - keep]:
                    del self.reports[h]
            doc = self.cp.to_json()
        # fsync outside the lock (blocking-under-lock): status polls and
        # worker folds must not stall on the checkpoint flush
        self.store.save_doc(doc)

    # -- daemon lifecycle ------------------------------------------------

    def run_background(self) -> "DASer":
        def loop() -> None:
            while not self._stop.is_set() \
                    and not self._halted_evt.is_set():
                try:
                    self.sync()
                except Exception as e:  # keep the daemon alive, loudly
                    log.error("sweep error", daser=self.name, err=e)
                # interruptible head-follow pause through the injected
                # clock: stop() wakes it immediately, and a VirtualClock
                # resolves it against simulated time
                self.clock.wait(self._stop, self.cfg.poll_interval)

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30)


class SweepCont:
    """One sweep of a DASer as an explicit continuation.

    The sweep's state machine — plan (halted gate + head follow + job
    split) → one catch-up job per step → fold (checkpoint + summary) —
    lives in this object instead of a per-DASer OS thread, so a
    scheduler advances thousands of samplers by calling ``step()`` one
    bounded unit at a time (sim/engine.SimLightNode). ``sync()`` drives
    the identical phases with its worker pool racing the job list; at
    ``workers=1`` the two drivers execute the exact same request/rng
    sequence (the tier-1 equivalence pin).

    Phases: ``plan`` → ``jobs`` → ``fold`` → ``done``. ``step()``
    returns True while more work remains; ``summary`` holds the sweep's
    return dict once done. The per-job rng lanes spawn off the DASer's
    parent generator with the same ``min(workers, len(pending))`` count
    the threaded pool uses, so a seeded DASer's parent stream stays
    byte-identical under either driver."""

    def __init__(self, daser: DASer):
        self.daser = daser
        self.phase = "plan"
        self.jobs: list[list[tuple[int, str, int]]] = []
        self.rngs: list = []
        # written under the DASER's lock (a foreign lock, out of the
        # lexical guarded-by rule's model): sync()'s worker threads
        # merge job results here concurrently; the continuation driver
        # is single-threaded and _fold runs strictly after the last job
        self.results: dict[int, dict] = {}
        self.summary: dict | None = None
        self._ji = 0  # next job index (continuation driver only)

    @property
    def done(self) -> bool:
        return self.phase == "done"

    def step(self) -> bool:
        """Run one bounded unit of the sweep; True while more remain."""
        if self.phase == "plan":
            self._plan()
        elif self.phase == "jobs":
            self._job()
        elif self.phase == "fold":
            self._fold()
        return self.phase != "done"

    def _finish(self, summary: dict) -> None:
        self.summary = summary
        self.phase = "done"

    def _plan(self) -> None:
        d = self.daser
        with d._lock:
            if d.cp.halted is not None:
                self._finish({"halted": d.cp.halted})
                return
        d._advance_head()
        with d._lock:
            if d.cp.halted is not None:
                # a condemned root surfaced during following
                self._finish({"halted": d.cp.halted})
                return
        pending = d._pending_heights()
        if not pending:
            self.phase = "fold"
            return
        self.jobs = [pending[i:i + d.cfg.job_size]
                     for i in range(0, len(pending), d.cfg.job_size)]
        # one independent child generator per worker lane (spawn keys
        # off the parent's seed sequence, so a seeded DASer stays
        # deterministic while lanes never share bit-generator state)
        self.rngs = list(d.rng.spawn(min(d.cfg.workers, len(pending))))
        self.phase = "jobs"

    def _job(self) -> None:
        d = self.daser
        if self._ji >= len(self.jobs) or d._stop.is_set() \
                or d._halted_evt.is_set():
            self.phase = "fold"
            return
        job = self.jobs[self._ji]
        # round-robin lane assignment: job i runs on lane i % n — at
        # workers=1 this is the threaded pool's exact FIFO order
        rng = self.rngs[self._ji % len(self.rngs)]
        self._ji += 1
        reps = d._sweep_job(job, rng)
        with d._lock:
            self.results.update(reps)
            d.reports.update(reps)
        if self._ji >= len(self.jobs):
            self.phase = "fold"

    def _fold(self) -> None:
        d = self.daser
        d._fold(self.results)
        with d._lock:
            self._finish({
                "head": d.cp.network_head,
                "sample_from": d.cp.sample_from,
                "sampled": sorted(h for h, r in self.results.items()
                                  if r["status"] in ("sampled",
                                                     "recovered")),
                "failed": sorted(d.cp.failed),
                "halted": d.cp.halted,
            })
