"""Proof packs: content-addressed, pre-assembled sample-proof bundles.

The serving half of arXiv:1910.01247's light-client model — many dumb
samplers hitting a *static* commitment — taken literally: at warm time
(the moment `da/edscache.ProverWarmer` already owns) a full node
precomputes EVERY cell's share + proof for a committed height in the
scheme's wire encoding and writes the bundle under

    <home>/packs/<data_root_hex>/
        <sha256(chunk)>.chunk ...     fsync'd, content-named chunks
        manifest.json                 written LAST (tmp+fsync+rename)

so serving a sample becomes `open(); read(); write()` — no lock, no
proof assembly, no JSON encoding per cell — and any blob store or CDN
can front the light-client fleet by mirroring the directory. The layout
is the sync plane's chunk pattern (chain/sync.py) with the chunk files
named by their OWN sha256 instead of an index: a pack is a pure function
of the data root, so mirrors can dedupe and a reader can verify every
byte against the manifest it fetched.

Byte-identity contract: each chunk is the canonical JSON encoding of a
list of per-cell sample docs, and each doc is built by the SAME
``live_cell_doc`` the live `/das/samples` path uses — pack-served proofs
are byte-identical to live-assembled ones by construction, and pinned
per scheme in tier-1 (tests/test_serving.py).

Crash safety: chunks are fsync'd as they land and the manifest goes last
via tmp+fsync+rename (``chain/sync.atomic_json_write`` — the
das/checkpoint.py discipline), so a crash mid-build leaves a dir with no
manifest: never advertised, never served, pruned on the next build. The
``packs.mid_write`` fault point (catalog: faults/__init__.py) fires
after each durable chunk so the chaos suite can kill a builder at the
torn moment and assert the node stays servable (live assembly).

Disk is bounded with the snapshot ``keep`` pattern: after every build
the store prunes to the newest ``CELESTIA_PACK_KEEP`` packs by the
height recorded in their manifests.

Wire formats: docs/FORMATS.md §17. Design: docs/DESIGN.md "The serving
plane".
"""

from __future__ import annotations

import base64
import hashlib
import json
import os
import shutil
import threading

from celestia_app_tpu.da import codec as codec_mod
from celestia_app_tpu.utils import telemetry

PACK_DIRNAME = "packs"

# bounded disk: keep the newest N packs (0 = keep everything)
DEFAULT_PACK_KEEP = int(os.environ.get("CELESTIA_PACK_KEEP", "4"))
# cells per chunk: small enough that a sampler's handful of draws maps
# to few chunks, big enough that a chunk amortizes its HTTP round-trip
DEFAULT_CHUNK_CELLS = int(os.environ.get("CELESTIA_PACK_CHUNK_CELLS",
                                         "256"))

MANIFEST_FIELDS = (
    "version", "height", "data_root", "scheme", "n_cells", "chunk_cells",
    "n_chunks", "chunk_hashes",
)


class PackError(ValueError):
    """Client-side problem on the /das/pack* surface (no pack for the
    height, bad chunk index); messages containing "not served" map to
    404 in the HTTP services."""


def live_cell_doc(entry, cell, prover=None) -> dict:
    """THE per-cell sample doc (FORMATS §7.1 / §16.3) — one builder
    shared by the live serving path (das/server.SampleCore) and the pack
    builder, so pack bytes ≡ live bytes by construction. ``prover`` lets
    the live path pass its memoized row prover; the default resolves the
    entry's own (engines are pinned bit-identical)."""
    if entry.scheme == codec_mod.RS2D_NAME:
        row, col = cell
        if prover is None:
            prover = entry.get_prover()
        share, proof = prover.prove_cell(row, col)
        return {
            "row": row,
            "col": col,
            "share": base64.b64encode(share).decode(),
            "proof": {
                "start": proof.start,
                "end": proof.end,
                "total": proof.total,
                "nodes": [base64.b64encode(n).decode()
                          for n in proof.nodes],
            },
        }
    # non-default schemes: the codec's own doc, with row/col aliases so
    # batched responses keep one shape across schemes (FORMATS §16.3)
    codec = codec_mod.get(entry.scheme)
    doc = codec.open_sample(entry, cell)
    return {"row": cell[0], "col": cell[1], **doc}


def encode_chunk(docs: list[dict]) -> bytes:
    """Canonical chunk bytes: sorted-key, separator-minimal JSON over the
    doc list — deterministic, so the chunk's sha256 is a pure function of
    the served proofs."""
    return json.dumps(docs, sort_keys=True,
                      separators=(",", ":")).encode()


def decode_chunk(data: bytes) -> list[dict]:
    """Parse chunk bytes back to the doc list; raises PackError on
    anything that is not a JSON list (UNTRUSTED input on the DASer
    side — hash verification happens before, doc verification after)."""
    try:
        docs = json.loads(data)
    except ValueError as e:
        raise PackError(f"undecodable pack chunk: {e}") from None
    if not isinstance(docs, list):
        raise PackError("pack chunk must be a JSON list of sample docs")
    return docs


def build_pack(entry, height: int,
               chunk_cells: int | None = None) -> tuple[dict, list[bytes]]:
    """(manifest, chunks) for one height's full sample-proof bundle.

    Cells are chunked in the codec's ``sample_space`` order (row-major
    for rs2d-nmt, layer-0 index order for cmt-ldpc), so a sampler maps a
    drawn cell to its chunk by position — no per-cell index table on the
    wire. The manifest carries the scheme's commitments doc, making a
    pack fully self-contained for a CDN-fronted sampler (it still
    verifies every proof against the CERTIFIED data root)."""
    chunk_cells = chunk_cells or DEFAULT_CHUNK_CELLS
    codec = codec_mod.get(entry.scheme)
    space = codec.sample_space(entry.dah)
    docs = [live_cell_doc(entry, cell) for cell in space]
    chunks = [
        encode_chunk(docs[i:i + chunk_cells])
        for i in range(0, len(docs), chunk_cells)
    ]
    manifest = {
        "version": 1,
        "height": height,
        "data_root": entry.data_root.hex(),
        "scheme": entry.scheme,
        "n_cells": len(space),
        "chunk_cells": chunk_cells,
        "n_chunks": len(chunks),
        "chunk_hashes": [hashlib.sha256(c).hexdigest() for c in chunks],
        "commitments": codec.commitments_doc(entry),
    }
    return manifest, chunks


def _manifest_ok(m) -> bool:
    if not isinstance(m, dict):
        return False
    if any(k not in m for k in MANIFEST_FIELDS):
        return False
    return (isinstance(m["chunk_hashes"], list)
            and len(m["chunk_hashes"]) == m["n_chunks"])


def advertised(manifest: dict) -> dict:
    """The compact pack advertisement riding the /das/header doc (the
    sampler's zero-extra-round-trip discovery): everything a chunk
    fetcher needs, without the commitments doc the header already
    carries."""
    return {k: manifest[k] for k in MANIFEST_FIELDS}


class PackStore:
    """The on-disk pack set one node serves (``<home>/packs``).

    Read paths touch only the filesystem plus a small manifest memo —
    serving a manifest or chunk never takes any app/service lock. Packs
    are immutable once their manifest lands (content-addressed by data
    root), so the memo never needs invalidation; it is bounded LRU all
    the same."""

    _MEMO_MAX = 16

    def __init__(self, root: str, keep: int | None = None,
                 chunk_cells: int | None = None):
        self.root = root
        self.keep = DEFAULT_PACK_KEEP if keep is None else int(keep)
        self.chunk_cells = chunk_cells or DEFAULT_CHUNK_CELLS
        self._lock = threading.Lock()
        # data_root hex -> manifest (immutable docs; bounded)
        self._memo: dict[str, dict] = {}  # guarded-by: _lock

    # -- lookup ----------------------------------------------------------

    def path_for(self, root_hex: str) -> str:
        return os.path.join(self.root, root_hex)

    def manifest(self, data_root: bytes | str) -> dict | None:
        """The pack manifest for a data root, or None when no complete
        pack exists (half-written dirs have no manifest and never
        serve)."""
        root_hex = (data_root.hex() if isinstance(data_root, bytes)
                    else data_root)
        with self._lock:
            hit = self._memo.get(root_hex)
        if hit is not None:
            return hit
        path = os.path.join(self.path_for(root_hex), "manifest.json")
        try:
            with open(path) as f:
                m = json.load(f)
        except (OSError, ValueError):
            return None
        if not _manifest_ok(m):
            return None
        with self._lock:
            while len(self._memo) >= self._MEMO_MAX:
                self._memo.pop(next(iter(self._memo)))
            self._memo[root_hex] = m
        return m

    def chunk(self, data_root: bytes | str, index: int) -> bytes:
        """Raw chunk bytes from disk — the /das/pack/chunk body. Raises
        PackError('... not served') when the pack/chunk is absent."""
        m = self.manifest(data_root)
        root_hex = (data_root.hex() if isinstance(data_root, bytes)
                    else data_root)
        if m is None:
            raise PackError(f"pack {root_hex[:16]} not served")
        if not 0 <= int(index) < m["n_chunks"]:
            raise PackError(
                f"pack chunk index {index} out of range "
                f"(n_chunks {m['n_chunks']})"
            )
        path = os.path.join(self.path_for(root_hex),
                            m["chunk_hashes"][int(index)] + ".chunk")
        try:
            with open(path, "rb") as f:
                return f.read()
        except OSError:
            raise PackError(
                f"pack chunk {root_hex[:16]}/{index} not served"
            ) from None

    # -- build / prune ---------------------------------------------------

    def build(self, height: int, entry) -> dict | None:
        """Build + durably persist the height's pack (idempotent: an
        existing complete pack for the same data root is left alone —
        packs are pure functions of the root). Returns the manifest, or
        the resident one on skip. Fires ``packs.mid_write`` after each
        durable chunk; a crash/error there leaves no manifest, so the
        half-pack is never served and the next build restarts it."""
        from celestia_app_tpu import faults

        existing = self.manifest(entry.data_root)
        if existing is not None:
            telemetry.incr("packs.build_skipped")
            return existing
        t0 = telemetry.start_timer()
        manifest, chunks = build_pack(entry, height, self.chunk_cells)
        from celestia_app_tpu.chain.sync import (
            atomic_json_write,
            fsync_write,
        )

        out_dir = self.path_for(manifest["data_root"])
        os.makedirs(out_dir, exist_ok=True)
        for i, chunk in enumerate(chunks):
            fsync_write(
                os.path.join(out_dir, manifest["chunk_hashes"][i]
                             + ".chunk"),
                chunk,
            )
            telemetry.incr("packs.chunks_written")
            # crash point: THIS chunk is durable, the manifest is not —
            # the pack must stay invisible to /das/pack until it is
            action = faults.fire("packs.mid_write", height=height,
                                 data_root=manifest["data_root"],
                                 index=i)
            if action in ("drop", "error"):
                raise OSError("injected fault: packs.mid_write")
        atomic_json_write(os.path.join(out_dir, "manifest.json"),
                          manifest)
        telemetry.incr("packs.built")
        telemetry.measure_since("packs.build", t0)
        self.prune(self.keep)
        return manifest

    def prune(self, keep: int) -> None:
        """Keep only the newest ``keep`` complete packs (by manifest
        height; 0 = keep everything). A manifest-less dir — a crashed
        build — is deleted outright and never counts toward the kept
        set (the snapshot-prune semantics, chain/sync.prune_snapshots)."""
        if not os.path.isdir(self.root):
            return
        complete: list[tuple[int, str]] = []
        for name in sorted(os.listdir(self.root)):
            path = os.path.join(self.root, name)
            if not os.path.isdir(path):
                continue
            m = self.manifest(name)
            if m is None:
                shutil.rmtree(path, ignore_errors=True)
                telemetry.incr("packs.pruned_torn")
                continue
            complete.append((int(m["height"]), name))
        if keep <= 0:
            return
        for _h, name in sorted(complete, reverse=True)[keep:]:
            shutil.rmtree(os.path.join(self.root, name),
                          ignore_errors=True)
            with self._lock:
                self._memo.pop(name, None)
            telemetry.incr("packs.pruned")
