"""DASer checkpoint: durable sampling progress for a light node.

The celestia-node DASer persists a checkpoint (SampleFrom / NetworkHead /
Failed map) so a restarted daemon resumes where it left off instead of
resampling the chain; this is that record, with the same fsync-before-
replace discipline every per-height artifact in this repo uses
(chain/reactor.py commit records, consensus.py sign state). File format
is normative — docs/FORMATS.md §7.3.

`halted` is the terminal record: a verified bad-encoding fraud proof (or
an operator decision) condemned a height, and this node must not follow
the chain past it until the checkpoint is cleared by hand.
"""

from __future__ import annotations

import dataclasses
import json
import os


@dataclasses.dataclass
class Checkpoint:
    sample_from: int = 1  # first height NOT yet durably sampled
    network_head: int = 0  # highest header this node has verified
    failed: dict[int, int] = dataclasses.field(default_factory=dict)
    # height -> attempts; retried on later sweeps
    halted: dict | None = None
    # {"height": H, "reason": "bad-encoding"|..., "data_root": hex}

    def to_json(self) -> dict:
        return {
            "version": 1,
            "sample_from": self.sample_from,
            "network_head": self.network_head,
            "failed": {str(h): n for h, n in sorted(self.failed.items())},
            "halted": self.halted,
        }

    @staticmethod
    def from_json(doc: dict) -> "Checkpoint":
        if int(doc.get("version", 1)) != 1:
            raise ValueError(f"unknown checkpoint version {doc.get('version')}")
        return Checkpoint(
            sample_from=int(doc.get("sample_from", 1)),
            network_head=int(doc.get("network_head", 0)),
            failed={int(h): int(n)
                    for h, n in (doc.get("failed") or {}).items()},
            halted=doc.get("halted"),
        )


class CheckpointStore:
    """One checkpoint file, written atomically (tmp + fsync + replace) —
    a crash mid-save leaves the previous checkpoint intact, so the DASer
    can only ever UNDER-claim progress, never skip heights."""

    def __init__(self, path: str):
        self.path = path

    def load(self) -> Checkpoint:
        if not os.path.exists(self.path):
            return Checkpoint()
        with open(self.path) as f:
            return Checkpoint.from_json(json.load(f))

    def save(self, cp: Checkpoint) -> None:
        self.save_doc(cp.to_json())

    def save_doc(self, doc: dict) -> None:
        """Write an already-snapshotted ``Checkpoint.to_json()`` doc —
        callers that guard their checkpoint with a lock snapshot under
        the lock and pay the fsync OUTSIDE it (blocking-under-lock)."""
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
