"""Blob read plane: batched namespace-query serving for rollup readers.

The serving half of the reference's ``pkg/proof`` + x/blob query surface
at the north star's scale: most users are rollup nodes reading their
namespace's blobs with inclusion (or absence) proofs, so the read path
must resolve MANY (namespace, height) queries per round-trip off the
resident NMT level stacks (da/namespace_device.py), never by a per-query
square scan.

Routes (mounted on the node HTTP service, the validator server, and the
standalone blob-serve sidecar; wire format in docs/FORMATS.md §21):

  GET  /blob/get?height=H&namespace=HEX    one namespace's shares +
                                           presence/absence proof
  POST /blob/namespaces {queries: [{height, namespace}...]}
                                           batched multi-query variant:
                                           entries resolved in ONE pass,
                                           search dispatched per height
                                           batch, response keeps request
                                           order, each member
                                           byte-identical to /blob/get
  GET  /blob/pack?height=H                 blob-pack manifest (§21.2)
  GET  /blob/pack/chunk?height=H&index=I   raw pack chunk bytes — static
                                           serving, no lock, no assembly

Absence is a first-class answer, not a 404: an empty-namespace query
returns {"present": false} with the absence witness
(da/namespace_data.verify_namespace_data semantics — a successor-leaf
proof for a straddling row, or no proof when every row window excludes
the target), so a follower can prove its namespace had NO blobs at a
height. Telemetry: ``blob.namespace_queries`` / ``blob.namespace_batches``
/ ``blob.absence_proofs`` / ``blob.pack_hits`` / ``blob.pack_misses``
plus the ``blob.batch_size`` histogram — surfaced on /metrics and both
status surfaces via ``status_block``.

Entries come from the DAS serving plane's SampleCore (single-flight
builds, commit-warmer seeding), so the read plane shares the sample
plane's cache discipline instead of duplicating it.
"""

from __future__ import annotations

import threading

from celestia_app_tpu.da import codec as codec_mod
from celestia_app_tpu.das import blob_packs as blob_packs_mod
from celestia_app_tpu.das.server import SampleCore, SampleError
from celestia_app_tpu.utils import telemetry


class BlobError(SampleError):
    """Client-side problem on the /blob/* surface; messages containing
    "not served" map to 404 in the HTTP services (the SampleError
    convention, so every mounting transport reuses one handler)."""


class BlobCore:
    """Namespace-read serving over the DAS plane's entry cache.

    Thread-safe: handler threads call `get`/`namespaces_many`
    concurrently; entry resolution single-flights through the shared
    SampleCore and the batched search runs on immutable level arrays."""

    def __init__(self, core: SampleCore,
                 pack_store: "blob_packs_mod.BlobPackStore | None" = None):
        self.core = core
        self.app = core.app
        # the static blob-pack store (das/blob_packs.py): built at warm
        # time by the app's ProverWarmer, served here as raw bytes.
        self.pack_store = (pack_store if pack_store is not None
                           else getattr(core.app, "blob_pack_store", None))

    # -- entries ---------------------------------------------------------

    def _entry(self, height: int):
        entry = self.core._entry(height)
        if entry.scheme != codec_mod.RS2D_NAME:
            raise BlobError(
                f"namespace reads need the {codec_mod.RS2D_NAME} scheme; "
                f"height {height} is {entry.scheme}"
            )
        return entry

    @staticmethod
    def _parse_namespace(value) -> bytes:
        from celestia_app_tpu.da import namespace_device as nsdev

        if not isinstance(value, str):
            raise BlobError("namespace must be a hex string")
        try:
            return nsdev.parse_namespace(value)
        except ValueError as e:
            raise BlobError(str(e)) from None

    @staticmethod
    def _doc(height: int, entry, namespace: bytes, nd=None) -> dict:
        """One query's response member — the shared builder
        (das/blob_packs.live_namespace_doc), so the single-query
        response, every batch member, and the pack bytes all agree by
        construction."""
        doc = blob_packs_mod.live_namespace_doc(
            entry.cache_entry, namespace, prover=entry.prover, nd=nd)
        if not doc["present"]:
            telemetry.incr("blob.absence_proofs")
        return {"height": height, **doc}

    # -- serving ---------------------------------------------------------

    def get(self, height: int, namespace_hex: str) -> dict:
        """GET /blob/get: one namespace at one height, resolved with the
        host reference's per-query scan
        (da/namespace_data.get_namespace_data) — the per-request loop
        the batched route is benchmarked against (bench.py --read)."""
        namespace = self._parse_namespace(namespace_hex)
        entry = self._entry(height)
        telemetry.incr("blob.namespace_queries")
        telemetry.observe("blob.batch_size", 1.0)
        return self._doc(height, entry, namespace)

    def namespaces_many(self, queries) -> dict:
        """POST /blob/namespaces: resolve every query's height against
        the serving cache in ONE pass, then dispatch each height's
        namespaces as one batched search (da/namespace_device.py) —
        response keeps REQUEST order, each member byte-identical to the
        single-query response. A height that cannot be resolved yields
        {"height", "namespace", "error"} so the rest still serves."""
        from celestia_app_tpu.da import namespace_device as nsdev

        if not isinstance(queries, list) or not queries:
            raise BlobError("namespaces needs a non-empty 'queries' list")
        parsed: list[tuple[int, bytes]] = []
        for q in queries:
            try:
                height = int(q["height"])
            except (KeyError, TypeError, ValueError):
                raise BlobError(
                    "each query needs an integer 'height'") from None
            parsed.append((height, self._parse_namespace(
                q.get("namespace"))))
        telemetry.incr("blob.namespace_queries", len(parsed))
        telemetry.incr("blob.namespace_batches")
        telemetry.observe("blob.batch_size", float(len(parsed)))
        # resolve every entry first (single-flight per height) ...
        resolved: dict[int, object] = {}
        for height, _ns in parsed:
            if height in resolved:
                continue
            try:
                resolved[height] = self._entry(height)
            except SampleError as e:
                resolved[height] = e
        # ... then ONE batched search per resolved height
        nds: dict[int, dict[bytes, object]] = {}
        engine = self.core._engine()
        for height, entry in resolved.items():
            if isinstance(entry, SampleError):
                continue
            batch = []
            for h, ns in parsed:
                if h == height and ns not in batch:
                    batch.append(ns)
            got = nsdev.get_namespace_data_batched(
                entry.prover, batch, engine=engine)
            nds[height] = dict(zip(batch, got))
        out = []
        for height, ns in parsed:
            entry = resolved[height]
            if isinstance(entry, SampleError):
                out.append({"height": height, "namespace": ns.hex(),
                            "error": str(entry)})
                continue
            out.append(self._doc(height, entry, ns,
                                 nd=nds[height][ns]))
        return {"queries": out}

    # -- blob packs (static serving; das/blob_packs.py) ------------------

    def _pack_root(self, height: int) -> bytes:
        """The height's data root WITHOUT building a square: cached
        serving entries first, then the durable block store — pack
        routes must never trigger an extend (the SampleCore._pack_root
        rule, counted on the blob plane's own miss counter)."""
        with self.core._lock:
            hit = self.core._cache.get(height)
        if hit is not None:
            return hit.root
        db = getattr(self.app, "db", None)
        if db is not None:
            try:
                return db.load_block(height).header.data_hash
            except (OSError, KeyError, ValueError):
                pass
        telemetry.incr("blob.pack_misses")
        raise BlobError(f"blob pack for height {height} not served")

    def pack_manifest(self, height: int) -> dict:
        """GET /blob/pack: the height's blob-pack manifest, or a
        404-mapped refusal (counted blob.pack_misses — the reader falls
        back to the live query)."""
        if self.pack_store is None:
            telemetry.incr("blob.pack_misses")
            raise BlobError(f"blob pack for height {height} not served")
        m = self.pack_store.manifest(self._pack_root(height))
        if m is None:
            telemetry.incr("blob.pack_misses")
            raise BlobError(f"blob pack for height {height} not served")
        return m

    def pack_chunk(self, height: int, index: int) -> bytes:
        """GET /blob/pack/chunk: raw chunk bytes straight from disk —
        no lock, no assembly, no JSON; the CDN-shaped hot path. Counted
        blob.pack_hits (misses blob.pack_misses)."""
        if self.pack_store is None:
            telemetry.incr("blob.pack_misses")
            raise BlobError(f"blob pack for height {height} not served")
        try:
            data = self.pack_store.chunk(self._pack_root(height), index)
        except blob_packs_mod.PackError as e:
            telemetry.incr("blob.pack_misses")
            raise BlobError(str(e)) from None
        telemetry.incr("blob.pack_hits")
        return data


def status_block() -> dict:
    """The read plane's status-surface block (mounted under "blob" on
    /status and /consensus/status — the admission.status_block
    pattern)."""
    counters = telemetry.snapshot()["counters"]

    def g(name: str) -> int:
        return int(counters.get(name, 0))

    return {
        "namespace_queries": g("blob.namespace_queries"),
        "namespace_batches": g("blob.namespace_batches"),
        "absence_proofs": g("blob.absence_proofs"),
        "pack_hits": g("blob.pack_hits"),
        "pack_misses": g("blob.pack_misses"),
        "device_batches": g("blob.device_batches"),
        "device_fallbacks": g("blob.device_fallbacks"),
        "packs_built": g("blobpacks.built"),
        "pack_build_errors": g("blobpacks.build_errors"),
    }


# -- one router shared by every transport -----------------------------------


def route_blob(core: BlobCore, method: str, path: str,
               query: dict, payload: dict | None = None):
    """Dispatch a /blob/* request. `query` holds the GET params
    (strings); POST bodies arrive in `payload`. Raises BlobError (a
    SampleError) for every malformed input, so transports reuse their
    /das/* handler: "not served" maps to 404, the rest to 400. Returns
    a JSON-able dict — or raw ``bytes`` for /blob/pack/chunk."""

    def _int(src: dict, key: str) -> int:
        try:
            v = src[key]
            return int(v[0] if isinstance(v, list) else v)
        except (KeyError, IndexError, TypeError, ValueError):
            raise BlobError(f"missing/invalid integer field {key!r}") \
                from None

    def _str(src: dict, key: str) -> str:
        v = src.get(key, "")
        return v[0] if isinstance(v, list) else v

    if method == "GET":
        if path == "/blob/get":
            return core.get(_int(query, "height"),
                            _str(query, "namespace"))
        if path == "/blob/pack":
            return core.pack_manifest(_int(query, "height"))
        if path == "/blob/pack/chunk":
            return core.pack_chunk(_int(query, "height"),
                                   _int(query, "index"))
    elif method == "POST" and path == "/blob/namespaces":
        payload = payload or {}
        return core.namespaces_many(payload.get("queries"))
    raise BlobError(f"no blob route {method} {path}")


class BlobService:
    """Standalone HTTP server for the read plane — the blob-serve
    sidecar: point it at a full node's home and it answers rollup
    readers (blob routes AND the /das/* routes a follower needs for
    headers) with no chain process attached."""

    def __init__(self, core: BlobCore, host: str = "127.0.0.1",
                 port: int = 26661):
        import json
        from http.server import (
            BaseHTTPRequestHandler,
            ThreadingHTTPServer,
        )
        from urllib.parse import parse_qs, urlparse

        from celestia_app_tpu.das.server import route_das

        service = self
        self.core = core

        class Handler(BaseHTTPRequestHandler):
            # keep-alive (HTTP/1.1): readers hold persistent
            # connections; every response sets Content-Length
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def _send(self, code: int, obj) -> None:
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _send_raw(self, code: int, body: bytes) -> None:
                # pack chunks serve raw bytes (octet-stream, NOT base64)
                self.send_response(code)
                self.send_header("Content-Type",
                                 "application/octet-stream")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _route(self, method: str, payload: dict | None) -> None:
                parsed = urlparse(self.path)
                try:
                    if parsed.path.startswith("/blob/"):
                        out = route_blob(service.core, method,
                                         parsed.path,
                                         parse_qs(parsed.query), payload)
                    else:
                        out = route_das(service.core.core, method,
                                        parsed.path,
                                        parse_qs(parsed.query), payload)
                    if isinstance(out, bytes):
                        self._send_raw(200, out)
                    else:
                        self._send(200, out)
                except SampleError as e:
                    self._send(404 if "not served" in str(e) else 400,
                               {"error": str(e)})
                except Exception as e:  # never kill the serving thread
                    telemetry.incr("blob.server_errors")
                    self._send(500, {"error": f"{type(e).__name__}: {e}"})

            def do_GET(self):
                self._route("GET", None)

            def do_POST(self):
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    payload = json.loads(self.rfile.read(n) or b"{}")
                except ValueError:
                    self._send(400, {"error": "body must be JSON"})
                    return
                self._route("POST", payload)

        class Server(ThreadingHTTPServer):
            # reader fleets connect in bursts; the stdlib default
            # listen backlog of 5 resets most of a burst on arrival
            request_queue_size = 1024

        self._httpd = Server((host, port), Handler)
        self.port = self._httpd.server_address[1]

    def serve_background(self):
        t = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        t.start()
        return self

    def serve_forever(self):
        self._httpd.serve_forever()

    def shutdown(self):
        self._httpd.shutdown()
