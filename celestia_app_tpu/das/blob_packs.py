"""Blob packs: content-addressed, pre-assembled namespace-read bundles.

The read plane's static half (das/packs.py's pattern applied to the
rollup-reader workload): at warm time — the moment the ProverWarmer
already owns, provers built, level stacks resident — a full node
precomputes EVERY present blob namespace's full query response (shares +
presence-and-completeness proof, da/namespace_data.py) for a committed
height and writes the bundle under

    <home>/blobpacks/<data_root_hex>/
        <sha256(chunk)>.chunk ...     fsync'd, content-named chunks
        manifest.json                 written LAST (tmp+fsync+rename)

so serving a rollup follower becomes `open(); read(); write()` — no
lock, no proof assembly, no JSON encoding per query — and any blob
store or CDN can front the read fleet by mirroring the directory. A
pack is a pure function of the data root, so mirrors dedupe and a
reader verifies every byte against the manifest it fetched.

Byte-identity contract: each chunk is the canonical JSON encoding of a
list of per-namespace docs, and each doc is built by the SAME
``live_namespace_doc`` the live `/blob/get` path serves — pack bytes ≡
live bytes by construction, pinned in tests/test_read_plane.py.

Crash safety is the das/packs.py discipline verbatim: chunks fsync as
they land, the manifest goes last via tmp+fsync+rename, so a crash
mid-build leaves a manifest-less dir — never advertised, never served,
pruned on the next build. The ``blobpacks.mid_write`` fault point
(catalog: faults/__init__.py) fires after each durable chunk. Disk is
bounded with the keep-newest-N prune.

Wire formats: docs/FORMATS.md §21. Design: docs/DESIGN.md "The read
plane".
"""

from __future__ import annotations

import base64
import hashlib
import json
import os
import shutil
import threading

from celestia_app_tpu.da import codec as codec_mod
from celestia_app_tpu.das.packs import PackError, decode_chunk, encode_chunk
from celestia_app_tpu.utils import telemetry

BLOB_PACK_DIRNAME = "blobpacks"

# bounded disk: keep the newest N blob packs (0 = keep everything)
DEFAULT_BLOB_PACK_KEEP = int(os.environ.get("CELESTIA_BLOB_PACK_KEEP",
                                            "4"))
# namespaces per chunk: a follower fetches THE chunk covering its one
# namespace, so small chunks keep reads cheap while still amortizing
# the HTTP round-trip over a namespace neighborhood
DEFAULT_CHUNK_NAMESPACES = int(os.environ.get(
    "CELESTIA_BLOB_PACK_CHUNK_NS", "8"))

MANIFEST_FIELDS = (
    "version", "height", "data_root", "scheme", "n_namespaces",
    "namespaces", "chunk_namespaces", "n_chunks", "chunk_hashes",
)

__all__ = [
    "BLOB_PACK_DIRNAME", "MANIFEST_FIELDS", "PackError", "encode_chunk",
    "decode_chunk", "live_namespace_doc", "blob_namespaces",
    "build_blob_pack", "advertised", "BlobPackStore",
]


def live_namespace_doc(entry, namespace: bytes, prover=None,
                       nd=None) -> dict:
    """THE per-namespace read doc (FORMATS §21.1) — one builder shared
    by the live serving path (das/blob_server.BlobCore) and the pack
    builder, so pack bytes ≡ live bytes by construction. ``prover``
    lets callers pass a resolved prover; ``nd`` lets the batched route
    pass an already-resolved `NamespaceData` (batched resolution is
    pinned byte-identical to the host reference, so the doc bytes are
    unchanged)."""
    from celestia_app_tpu.chain.query import _share_proof_json
    from celestia_app_tpu.da import namespace_data as nsd_mod

    if nd is None:
        if prover is None:
            prover = entry.get_prover()
        nd = nsd_mod.get_namespace_data(prover, namespace)
    return {
        "namespace": namespace.hex(),
        "present": bool(nd.shares),
        "shares": [base64.b64encode(s).decode() for s in nd.shares],
        "proof": _share_proof_json(nd.proof) if nd.proof else None,
        "data_root": entry.data_root.hex(),
    }


def blob_namespaces(entry, prover=None) -> list[bytes]:
    """The height's packable namespaces: every DISTINCT unreserved
    namespace present in the Q0 square, in square (= lexicographic)
    order — read off the prover's resident level-0 mins, the same
    source the batched search uses."""
    from celestia_app_tpu.da import namespace as ns_mod
    from celestia_app_tpu.da import namespace_device as nsdev

    if prover is None:
        prover = entry.get_prover()
    leaf = nsdev.leaf_namespaces(prover)
    import numpy as np

    distinct = np.unique(leaf, axis=0)
    out = []
    for row in distinct:
        raw = row.tobytes()
        if not ns_mod.Namespace(raw).is_reserved():
            out.append(raw)
    return out


def build_blob_pack(entry, height: int,
                    chunk_namespaces: int | None = None
                    ) -> tuple[dict, list[bytes]]:
    """(manifest, chunks) for one height's full namespace-read bundle.

    Namespaces are chunked in square order, so a reader maps its
    namespace to a chunk by position in the manifest's ``namespaces``
    list — no per-namespace index table on the wire. Only the default
    scheme packs (namespace reads are an rs2d-nmt surface)."""
    if entry.scheme != codec_mod.RS2D_NAME:
        raise PackError(
            f"blob packs need the {codec_mod.RS2D_NAME} scheme, "
            f"not {entry.scheme}"
        )
    chunk_namespaces = chunk_namespaces or DEFAULT_CHUNK_NAMESPACES
    prover = entry.get_prover()
    spaces = blob_namespaces(entry, prover=prover)
    docs = [live_namespace_doc(entry, ns, prover=prover) for ns in spaces]
    chunks = [
        encode_chunk(docs[i:i + chunk_namespaces])
        for i in range(0, len(docs), chunk_namespaces)
    ]
    manifest = {
        "version": 1,
        "height": height,
        "data_root": entry.data_root.hex(),
        "scheme": entry.scheme,
        "n_namespaces": len(spaces),
        "namespaces": [ns.hex() for ns in spaces],
        "chunk_namespaces": chunk_namespaces,
        "n_chunks": len(chunks),
        "chunk_hashes": [hashlib.sha256(c).hexdigest() for c in chunks],
    }
    return manifest, chunks


def _manifest_ok(m) -> bool:
    if not isinstance(m, dict):
        return False
    if any(k not in m for k in MANIFEST_FIELDS):
        return False
    return (isinstance(m["chunk_hashes"], list)
            and len(m["chunk_hashes"]) == m["n_chunks"]
            and isinstance(m["namespaces"], list)
            and len(m["namespaces"]) == m["n_namespaces"])


def advertised(manifest: dict) -> dict:
    """The pack advertisement a reader needs to map its namespace to a
    chunk (FORMATS §21.2) — the manifest's normative fields."""
    return {k: manifest[k] for k in MANIFEST_FIELDS}


class BlobPackStore:
    """The on-disk blob-pack set one node serves (``<home>/blobpacks``).

    Read paths touch only the filesystem plus a small manifest memo —
    serving a manifest or chunk never takes any app/service lock. Packs
    are immutable once their manifest lands (content-addressed by data
    root), so the memo never needs invalidation; bounded LRU all the
    same."""

    _MEMO_MAX = 16

    def __init__(self, root: str, keep: int | None = None,
                 chunk_namespaces: int | None = None):
        self.root = root
        self.keep = DEFAULT_BLOB_PACK_KEEP if keep is None else int(keep)
        self.chunk_namespaces = (chunk_namespaces
                                 or DEFAULT_CHUNK_NAMESPACES)
        self._lock = threading.Lock()
        # data_root hex -> manifest (immutable docs; bounded)
        self._memo: dict[str, dict] = {}  # guarded-by: _lock

    # -- lookup ----------------------------------------------------------

    def path_for(self, root_hex: str) -> str:
        return os.path.join(self.root, root_hex)

    def manifest(self, data_root: bytes | str) -> dict | None:
        """The pack manifest for a data root, or None when no complete
        pack exists (half-written dirs have no manifest and never
        serve)."""
        root_hex = (data_root.hex() if isinstance(data_root, bytes)
                    else data_root)
        with self._lock:
            hit = self._memo.get(root_hex)
        if hit is not None:
            return hit
        path = os.path.join(self.path_for(root_hex), "manifest.json")
        try:
            with open(path) as f:
                m = json.load(f)
        except (OSError, ValueError):
            return None
        if not _manifest_ok(m):
            return None
        with self._lock:
            while len(self._memo) >= self._MEMO_MAX:
                self._memo.pop(next(iter(self._memo)))
            self._memo[root_hex] = m
        return m

    def chunk(self, data_root: bytes | str, index: int) -> bytes:
        """Raw chunk bytes from disk — the /blob/pack/chunk body.
        Raises PackError('... not served') when the pack/chunk is
        absent."""
        m = self.manifest(data_root)
        root_hex = (data_root.hex() if isinstance(data_root, bytes)
                    else data_root)
        if m is None:
            raise PackError(f"blob pack {root_hex[:16]} not served")
        if not 0 <= int(index) < m["n_chunks"]:
            raise PackError(
                f"blob pack chunk index {index} out of range "
                f"(n_chunks {m['n_chunks']})"
            )
        path = os.path.join(self.path_for(root_hex),
                            m["chunk_hashes"][int(index)] + ".chunk")
        try:
            with open(path, "rb") as f:
                return f.read()
        except OSError:
            raise PackError(
                f"blob pack chunk {root_hex[:16]}/{index} not served"
            ) from None

    # -- build / prune ---------------------------------------------------

    def build(self, height: int, entry) -> dict | None:
        """Build + durably persist the height's blob pack (idempotent:
        an existing complete pack for the same data root is left alone).
        Returns the manifest, the resident one on skip, or None for a
        scheme that does not pack. Fires ``blobpacks.mid_write`` after
        each durable chunk; a crash/error there leaves no manifest, so
        the half-pack is never served and the next build restarts it."""
        from celestia_app_tpu import faults

        if entry.scheme != codec_mod.RS2D_NAME:
            return None
        existing = self.manifest(entry.data_root)
        if existing is not None:
            telemetry.incr("blobpacks.build_skipped")
            return existing
        t0 = telemetry.start_timer()
        manifest, chunks = build_blob_pack(entry, height,
                                           self.chunk_namespaces)
        from celestia_app_tpu.chain.sync import (
            atomic_json_write,
            fsync_write,
        )

        out_dir = self.path_for(manifest["data_root"])
        os.makedirs(out_dir, exist_ok=True)
        for i, chunk in enumerate(chunks):
            fsync_write(
                os.path.join(out_dir, manifest["chunk_hashes"][i]
                             + ".chunk"),
                chunk,
            )
            telemetry.incr("blobpacks.chunks_written")
            # crash point: THIS chunk is durable, the manifest is not —
            # the pack must stay invisible to /blob/pack until it is
            action = faults.fire("blobpacks.mid_write", height=height,
                                 data_root=manifest["data_root"],
                                 index=i)
            if action in ("drop", "error"):
                raise OSError("injected fault: blobpacks.mid_write")
        atomic_json_write(os.path.join(out_dir, "manifest.json"),
                          manifest)
        telemetry.incr("blobpacks.built")
        telemetry.measure_since("blobpacks.build", t0)
        self.prune(self.keep)
        return manifest

    def prune(self, keep: int) -> None:
        """Keep only the newest ``keep`` complete packs (by manifest
        height; 0 = keep everything). A manifest-less dir — a crashed
        build — is deleted outright and never counts toward the kept
        set."""
        if not os.path.isdir(self.root):
            return
        complete: list[tuple[int, str]] = []
        for name in sorted(os.listdir(self.root)):
            path = os.path.join(self.root, name)
            if not os.path.isdir(path):
                continue
            m = self.manifest(name)
            if m is None:
                shutil.rmtree(path, ignore_errors=True)
                telemetry.incr("blobpacks.pruned_torn")
                continue
            complete.append((int(m["height"]), name))
        if keep <= 0:
            return
        for _h, name in sorted(complete, reverse=True)[keep:]:
            shutil.rmtree(os.path.join(self.root, name),
                          ignore_errors=True)
            with self._lock:
                self._memo.pop(name, None)
            telemetry.incr("blobpacks.pruned")
