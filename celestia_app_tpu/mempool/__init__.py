"""Mempool plane: the content-addressable CAT pool + want/have gossip.

- `pool.py`    — CATPool: hash-keyed store, priority reap, caps/TTL/recheck
- `gossip.py`  — SeenTx/WantTx/Tx protocol state (reactor owns transport)
- `metrics.py` — per-pool counters + process gauges into utils/telemetry
"""

from celestia_app_tpu.mempool.gossip import MempoolGossip
from celestia_app_tpu.mempool.metrics import MempoolMetrics
from celestia_app_tpu.mempool.pool import (
    CATPool,
    EntryView,
    PoolTx,
    RawTxView,
    check_mempool_size,
    parse_tx_meta,
    priority_order,
    tx_hash,
)

__all__ = [
    "CATPool",
    "EntryView",
    "MempoolGossip",
    "MempoolMetrics",
    "PoolTx",
    "RawTxView",
    "check_mempool_size",
    "parse_tx_meta",
    "priority_order",
    "tx_hash",
]
