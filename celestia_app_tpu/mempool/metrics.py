"""Mempool plane metrics: admission/eviction counters + pool gauges.

Reference parity: celestia-core's mempool Metrics (mempool/metrics.go —
Size, SizeBytes, FailedTxs, EvictedTxs, RecheckTimes) plus the CAT
reactor's gossip counters. Each pool owns a MempoolMetrics instance that
keeps LOCAL counts (so N in-process validators stay distinguishable —
every test and /consensus/status reads per-node numbers) and mirrors every
event into the process-wide `utils/telemetry` registry, which the
prometheus endpoint and /status already serve.
"""

from __future__ import annotations

import time

from celestia_app_tpu.utils import telemetry

# counter names (local key == telemetry suffix under "mempool.")
ADMITTED = "admitted"
REJECTED = "rejected"
DUPLICATE = "duplicate"
EVICTED = "evicted"
EXPIRED_HEIGHT = "expired_height"
EXPIRED_TIME = "expired_time"
RECHECK_DROPPED = "recheck_dropped"
COMMITTED = "committed"

_COUNTERS = (ADMITTED, REJECTED, DUPLICATE, EVICTED, EXPIRED_HEIGHT,
             EXPIRED_TIME, RECHECK_DROPPED, COMMITTED)


class MempoolMetrics:
    def __init__(self, registry=None):
        # registry=None -> the module-global telemetry registry (what the
        # prometheus endpoint scrapes); tests may pass an isolated one
        self._reg = registry if registry is not None else telemetry._global
        self.counters: dict[str, int] = {c: 0 for c in _COUNTERS}

    def incr(self, name: str, by: int = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + by
        self._reg.incr(f"mempool.{name}", by)

    def set_size(self, count: int, nbytes: int) -> None:
        """Pool gauges after every mutation. In a multi-node process the
        global gauge is last-writer-wins; per-node truth is pool.stats()."""
        self._reg.gauge("mempool.pool_count", count)
        self._reg.gauge("mempool.pool_bytes", nbytes)

    def time_reap(self, t0: float) -> None:
        self._reg.measure_since("mempool.reap", t0)

    def now(self) -> float:  # one place to stub time in tests
        return time.perf_counter()

    def snapshot(self) -> dict[str, int]:
        return dict(self.counters)
