"""The content-addressable transaction pool (CAT) — THE mempool.

Reference parity: celestia-core's cat pool (mempool/cat/pool.go): every tx
is keyed by its sha256, admission runs CheckTx exactly once per content
(a duplicate submission returns the ORIGINAL result instead of re-running
ante against a bumped sequence and confusing the client), reaping orders
by gas price while preserving per-sender arrival order (mempool v1
priority semantics), the pool is capped by bytes AND count with
lowest-priority eviction, entries expire by TTL in heights and wall-clock
(TTLNumBlocks / TTLDuration, app/default_overrides.go:265-274), and after
every commit the survivors are RE-CHECKED against fresh state so
nonce-stale txs drop instead of wasting a proposal slot (RecheckTx).

All three former mempools route through this class: `chain/node.py` Node,
`chain/consensus.py` ValidatorNode, and the reactor's mempool-reactor half
(`chain/reactor.py` + `mempool/gossip.py`). One admission path, one
eviction policy, one recheck discipline.
"""

from __future__ import annotations

import dataclasses
import hashlib
import threading

from celestia_app_tpu import appconsts
from celestia_app_tpu.chain.block import TxResult
from celestia_app_tpu.mempool.metrics import (
    ADMITTED,
    COMMITTED,
    DUPLICATE,
    EVICTED,
    EXPIRED_HEIGHT,
    EXPIRED_TIME,
    REJECTED,
    RECHECK_DROPPED,
    MempoolMetrics,
)


def tx_hash(raw: bytes) -> bytes:
    """THE tx key: sha256 of the broadcast bytes (what blocks store, what
    GetTx/ConfirmTx look up, what SeenTx/WantTx gossip announces)."""
    return hashlib.sha256(raw).digest()


def check_mempool_size(raw: bytes) -> TxResult | None:
    """THE mempool byte-cap gate (MaxTxBytes, default_overrides.go:271-273),
    shared by every admission path so they can never disagree on which txs
    fit. None = within the cap."""
    if len(raw) > appconsts.MEMPOOL_MAX_TX_BYTES:
        return TxResult(1, "tx exceeds mempool max bytes", 0, 0, [])
    return None


def priority_order(items: list[tuple[bytes, float, bytes | None]]) -> list[bytes]:
    """Gas-price-descending reap that preserves PER-SENDER arrival order.

    `items` = [(raw, gas_price, sender)] in arrival order. A plain
    (-price, arrival) sort would let a sender's later high-fee tx jump its
    own earlier low-fee one — the later tx then fails the ante sequence
    check in the proposal filter and is pointlessly delayed a height. Here
    the sorted positions are kept, but each position is filled with the
    owning sender's OLDEST pending tx, so priority decides which sender
    goes first while nonces stay in submission order."""
    from collections import deque

    def key(i: int):
        sender = items[i][2]
        return sender if sender is not None else (b"raw", items[i][0])

    queues: dict = {}
    for i, (raw, _price, _sender) in enumerate(items):
        queues.setdefault(key(i), deque()).append(raw)
    order = sorted(range(len(items)), key=lambda i: (-items[i][1], i))
    return [queues[key(i)].popleft() for i in order]


def parse_tx_meta(raw: bytes) -> tuple[float, bytes | None]:
    """(fee/gas, signer pubkey) for priority + per-sender lanes; junk that
    somehow passed CheckTx degrades to zero-priority, anonymous."""
    from celestia_app_tpu.chain.tx import decode_tx
    from celestia_app_tpu.da import blob as blob_mod

    try:
        btx = blob_mod.try_unmarshal_blob_tx(raw)
        tx = decode_tx(btx.tx if btx is not None else raw)
        return (tx.body.fee / tx.body.gas_limit, tx.pubkey)
    except (ValueError, ZeroDivisionError):
        return (0.0, None)


@dataclasses.dataclass
class PoolTx:
    raw: bytes
    hash: bytes
    gas_price: float
    sender: bytes | None  # signer pubkey; keys the per-sender FIFO lane
    height_added: int
    time_added: float
    seq: int  # arrival order, pool-global
    result: TxResult  # the ORIGINAL CheckTx verdict (duplicate returns)


class CATPool:
    """Content-addressable priority mempool; see module docstring."""

    def __init__(
        self,
        max_pool_bytes: int = appconsts.MEMPOOL_MAX_POOL_BYTES,
        max_txs: int = appconsts.MEMPOOL_MAX_TXS,
        ttl_blocks: int = appconsts.MEMPOOL_TX_TTL_BLOCKS,
        ttl_seconds: float | None = appconsts.MEMPOOL_TX_TTL_SECONDS,
        metrics: MempoolMetrics | None = None,
        clock=None,
    ):
        self.max_pool_bytes = max_pool_bytes
        self.max_txs = max_txs
        self.ttl_blocks = ttl_blocks
        self.ttl_seconds = ttl_seconds  # None disables wall-clock TTL
        self.metrics = metrics or MempoolMetrics()
        # THE wall-clock TTL time source (utils/clock.py): SystemClock by
        # default; a simulated pool takes the scenario's VirtualClock so
        # TTL expiry runs on virtual seconds, deterministically. Public —
        # embedders (the scenario plane) re-point it after construction.
        from celestia_app_tpu.utils import clock as clock_mod

        self.clock = clock if clock is not None else clock_mod.SYSTEM
        # reentrant: public methods hold it across calls into each other
        # (add -> expire, reap -> expire). HTTP handler threads, the
        # reactor's gossip threads, and the node loop all touch the pool
        # concurrently — membership, byte accounting, and the seq counter
        # must move together.
        self._lock = threading.RLock()
        self._txs: dict[bytes, PoolTx] = {}  # guarded-by: _lock  (hash -> entry, arrival-ordered)
        self._bytes = 0                      # guarded-by: _lock
        self._next_seq = 0                   # guarded-by: _lock

    # -- introspection ---------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._txs)

    def __contains__(self, key: bytes) -> bool:
        """Membership by tx hash (32 bytes) or raw tx bytes."""
        with self._lock:
            return (key in self._txs) if len(key) == 32 \
                else (tx_hash(key) in self._txs)

    def has(self, h: bytes) -> bool:
        with self._lock:
            return h in self._txs

    def get_raw(self, h: bytes) -> bytes | None:
        with self._lock:
            e = self._txs.get(h)
            return e.raw if e is not None else None

    @property
    def pool_bytes(self) -> int:
        with self._lock:
            return self._bytes

    def entries(self) -> list[PoolTx]:
        with self._lock:
            return list(self._txs.values())

    def raws(self) -> list[bytes]:
        with self._lock:
            return [e.raw for e in self._txs.values()]

    def stats(self) -> dict:
        with self._lock:
            return {
                "count": len(self._txs),
                "bytes": self._bytes,
                **self.metrics.snapshot(),
            }

    # -- mutation core ---------------------------------------------------

    def _insert_locked(self, raw: bytes, h: bytes, meta: tuple[float, bytes | None],
                height: int, now: float, result: TxResult) -> None:
        self._txs[h] = PoolTx(
            raw=raw, hash=h, gas_price=meta[0], sender=meta[1],
            height_added=height, time_added=now, seq=self._next_seq,
            result=result,
        )
        self._next_seq += 1
        self._bytes += len(raw)
        self.metrics.set_size(len(self._txs), self._bytes)

    def _drop_locked(self, h: bytes, counter: str | None) -> PoolTx | None:
        e = self._txs.pop(h, None)
        if e is None:
            return None
        self._bytes -= len(e.raw)
        if counter is not None:
            self.metrics.incr(counter)
        self.metrics.set_size(len(self._txs), self._bytes)
        return e

    def _lane_key(self, e: PoolTx):
        return e.sender if e.sender is not None else (b"raw", e.hash)

    def _eviction_plan_locked(self, incoming_price: float,
                       incoming_len: int) -> list[PoolTx] | None:
        """Plan (without mutating) the evictions that make room for an
        incoming tx; None = no legal plan, refuse the tx. Computed BEFORE
        CheckTx runs so a refused tx never touches the check state, and
        applied only AFTER CheckTx passes so an invalid tx cannot evict
        anything.

        Victims are always LANE TAILS (each sender's newest pending tx —
        dropping a lane's oldest entry would strand every later nonce
        behind a sequence gap), taken cheapest-tail first, and only while
        the tail is STRICTLY cheaper than the incoming tx — the pool never
        evicts an equal-or-better tx for a worse one (a tail shielding an
        older dust tx shields it legitimately: the dust entry cannot be
        dropped alone without wasting the whole lane behind it)."""
        count, nbytes = len(self._txs), self._bytes
        if (count + 1 <= self.max_txs
                and nbytes + incoming_len <= self.max_pool_bytes):
            return []
        lanes: dict = {}
        for e in self._txs.values():  # arrival-ordered -> lane order
            lanes.setdefault(self._lane_key(e), []).append(e)
        victims: list[PoolTx] = []
        while (count + 1 > self.max_txs
               or nbytes + incoming_len > self.max_pool_bytes):
            tails = [lane[-1] for lane in lanes.values() if lane]
            if not tails:
                return None  # incoming alone exceeds the byte cap
            victim = min(tails, key=lambda e: (e.gas_price, -e.seq))
            if victim.gas_price >= incoming_price:
                return None
            lanes[self._lane_key(victim)].pop()
            victims.append(victim)
            count -= 1
            nbytes -= len(victim.raw)
        return victims

    # -- the single admission path --------------------------------------

    def add(self, raw: bytes, *, height: int, now: float | None = None,
            check_fn=None, meta: tuple[float, bytes | None] | None = None,
            ) -> TxResult:
        """CheckTx + admission. Duplicate content returns the ORIGINAL
        TxResult without re-running CheckTx (content-addressable dedup —
        the same raw tx POSTed twice must not be appended twice, and must
        not get a spurious sequence-mismatch error from its own first
        copy's CheckTx bump). `check_fn` is App.check_tx (None skips the
        check — trusted re-injection paths only). `meta` optionally
        supplies a pre-parsed (gas_price, sender)."""
        now = self.clock.now() if now is None else now
        h = tx_hash(raw)
        if meta is None:
            meta = parse_tx_meta(raw)  # parse OUTSIDE the lock (pure)
        with self._lock:
            existing = self._txs.get(h)
            if existing is not None:
                self.metrics.incr(DUPLICATE)
                return existing.result
            oversize = check_mempool_size(raw)
            if oversize is not None:
                self.metrics.incr(REJECTED)
                return oversize
            if (len(self._txs) + 1 > self.max_txs
                    or self._bytes + len(raw) > self.max_pool_bytes):
                # at a cap: sweep TTL-expired entries before evicting
                # live ones (the sweep is O(n), so it runs only when
                # space is actually needed; reap() sweeps per proposal)
                self.expire(height, now)
            # capacity verdict BEFORE CheckTx: App.check_tx WRITES into
            # the persistent check state (sequence bump, fee deduction)
            # — running it for a tx the pool then refuses would desync
            # the sender's whole lane until the next commit resets it.
            # The lock is held across CheckTx so two admissions cannot
            # interleave their plans against the same victims.
            plan = self._eviction_plan_locked(meta[0], len(raw))
            if plan is None:
                self.metrics.incr(REJECTED)
                return TxResult(1, "mempool is full", 0, 0, [])
            if check_fn is not None:
                res = check_fn(raw)
                if res.code != 0:
                    self.metrics.incr(REJECTED)
                    return res
            else:
                res = TxResult(0, "", 0, 0, [])
            # evictions apply only now — an invalid tx must not evict
            for victim in plan:
                self._drop_locked(victim.hash, EVICTED)
            self._insert_locked(raw, h, meta, height, now, res)
            self.metrics.incr(ADMITTED)
            return res

    def add_batch(self, raws, *, height: int, now: float | None = None,
                  check_fn=None, prevalidate_fn=None) -> list[TxResult]:
        """Two-phase batched admission (the ROADMAP's two-phase admit):
        phase 1 runs the caller's STATELESS prevalidation over the
        not-yet-pooled txs as one batch — one device dispatch filling
        the verified-sig cache plus one filling the verified-commitment
        cache (chain/admission.py) — and phase 2 runs the standard
        stateful per-tx admission, whose CheckTx then hits the caches
        instead of re-verifying each signature and recomputing each
        blob's share commitment. Results align with `raws`;
        dedup/eviction semantics are exactly `add`'s."""
        if prevalidate_fn is not None:
            # membership probe outside phase 2's lock holds; a racing
            # duplicate only costs a cache lookup, never a double-verify
            fresh = [raw for raw in raws if not self.has(tx_hash(raw))]
            if fresh:
                prevalidate_fn(fresh)
        return [self.add(raw, height=height, now=now, check_fn=check_fn)
                for raw in raws]

    # -- lifecycle -------------------------------------------------------

    def expire(self, height: int, now: float | None = None) -> list[PoolTx]:
        """TTL sweep: drop entries older than ttl_blocks heights OR
        ttl_seconds wall-clock (both default to the reference's 5-block /
        5×goal-block-time shape). Returns the dropped entries."""
        now = self.clock.now() if now is None else now
        dropped: list[PoolTx] = []
        with self._lock:
            for e in list(self._txs.values()):
                if height - e.height_added >= self.ttl_blocks:
                    dropped.append(
                        self._drop_locked(e.hash, EXPIRED_HEIGHT))
                elif (self.ttl_seconds is not None
                      and now - e.time_added >= self.ttl_seconds):
                    dropped.append(
                        self._drop_locked(e.hash, EXPIRED_TIME))
        return dropped

    def reap(self, height: int, now: float | None = None) -> list[bytes]:
        """The proposal candidate list: TTL sweep, then gas-price-desc
        order with per-sender arrival order kept (priority_order — the
        order FilterTxs receives candidates in, mempool v1 semantics)."""
        t0 = self.metrics.now()
        with self._lock:
            self.expire(height, now)
            out = priority_order(
                [(e.raw, e.gas_price, e.sender)
                 for e in self._txs.values()]
            )
        self.metrics.time_reap(t0)
        return out

    def remove_committed(self, txs) -> int:
        """Drop txs that just committed (by content)."""
        n = 0
        with self._lock:
            for raw in txs:
                if self._drop_locked(tx_hash(raw), COMMITTED) is not None:
                    n += 1
        return n

    def recheck(self, check_fn) -> list[PoolTx]:
        """Post-commit recheck: re-run CheckTx on every survivor against
        the FRESH check state (reset at commit), in arrival order so a
        sender's queued nonce chain revalidates front-to-back. Entries the
        app now refuses (stale sequence, balance spent by a committed tx,
        fee floor moved) drop instead of wasting a proposal slot. Returns
        the dropped entries."""
        dropped: list[PoolTx] = []
        with self._lock:
            for e in sorted(self._txs.values(), key=lambda e: e.seq):
                res = check_fn(e.raw)
                if res.code != 0:
                    dropped.append(
                        self._drop_locked(e.hash, RECHECK_DROPPED))
        return dropped

    def clear(self) -> None:
        with self._lock:
            self._txs.clear()
            self._bytes = 0
            self.metrics.set_size(0, 0)


# ---------------------------------------------------------------------------
# List-compatible views: the pre-CAT mempools were bare lists and tests,
# tools, and the status surfaces touch them as such (`len(node.mempool)`,
# `node.mempool.clear()`, `vnode.mempool == []`). These wrappers keep that
# surface alive over the pool without copying it per access.
# ---------------------------------------------------------------------------


class _PoolView:
    def __init__(self, pool: CATPool):
        self._pool = pool

    def _items(self) -> list:
        raise NotImplementedError

    def __len__(self) -> int:
        return len(self._pool)

    def __bool__(self) -> bool:
        return len(self._pool) > 0

    def __iter__(self):
        return iter(self._items())

    def __getitem__(self, i):
        return self._items()[i]

    def __eq__(self, other) -> bool:
        return self._items() == list(other)

    def __repr__(self) -> str:
        return repr(self._items())

    def clear(self) -> None:
        self._pool.clear()


class RawTxView(_PoolView):
    """ValidatorNode.mempool compat: a list of raw tx bytes."""

    def _items(self) -> list[bytes]:
        return self._pool.raws()


class EntryView(_PoolView):
    """Node.mempool compat: a list of pool entries (MempoolTx-shaped:
    .raw/.gas_price/.height_added/.sender)."""

    def _items(self) -> list[PoolTx]:
        return self._pool.entries()
