"""SeenTx / WantTx / Tx — the CAT pool's want/have gossip protocol.

Reference parity: celestia-core's cat reactor (mempool/cat/reactor.go):
instead of flooding full tx bytes to every peer (O(peers × tx-bytes) per
hop, the pre-CAT behavior of chain/reactor.py's mempool half), a node that
admits a tx announces the 32-byte HASH (SeenTx) to peers that are not
known to have it; a peer that wants the content pulls it from an announcer
(WantTx), and the announcer delivers the bytes (Tx) exactly once per edge
that asked. Per-peer have-sets suppress re-announcing to a peer that told
us it has the tx, and redundant-want suppression keeps one outstanding
pull per hash however many peers announce it.

This class is TRANSPORT-AGNOSTIC protocol state: the consensus reactor
(chain/reactor.py) owns the sockets and calls in; every decision that
matters — whom to announce to, whether to pull, whom to pull from next
after a failure — is made (and unit-testable) here. Wire formats are
normative in docs/FORMATS.md §8.

Byte accounting is per-instance (`stats`): tests and the devnet monitor
compare tx-payload bytes moved under want/have against the flood
equivalent, per node — the process-global telemetry registry would blur
N in-process validators together.
"""

from __future__ import annotations

from celestia_app_tpu.utils import telemetry


class MempoolGossip:
    """Want/have state for one node; see module docstring."""

    MAX_TRACKED = 8192  # hashes tracked for dedup/have-sets (bounded)

    def __init__(self, pool, peers: list[str], self_url: str):
        self.pool = pool
        self.peers = list(peers)
        self.self_url = self_url
        # hash -> set of peer urls known to HAVE the tx (they announced it
        # to us or pulled it from us); insertion-ordered for pruning
        self._have: dict[bytes, set[str]] = {}
        # outstanding pulls: hash -> remaining candidate providers (the
        # first announcer is being pulled; later announcers queue here so
        # a failed pull falls through instead of re-requesting in parallel)
        self._wanted: dict[bytes, list[str]] = {}
        # hashes this node has fully processed (admitted OR rejected):
        # a re-announce of either must not trigger another pull
        self._seen: dict[bytes, None] = {}
        self.stats = {
            "seen_sent": 0, "seen_recv": 0,
            "want_sent": 0, "want_suppressed": 0,
            "tx_bytes_sent": 0, "tx_bytes_recv": 0,
            "tx_served": 0, "tx_pulled": 0,
        }

    # -- bookkeeping -----------------------------------------------------

    def _bump(self, name: str, by: int = 1) -> None:
        self.stats[name] += by
        telemetry.incr(f"mempool.gossip.{name}", by)

    def _note_have(self, h: bytes, peer: str) -> None:
        self._have.setdefault(h, set()).add(peer)
        if len(self._have) > self.MAX_TRACKED:
            for k in list(self._have)[: self.MAX_TRACKED // 2]:
                del self._have[k]

    def seen(self, h: bytes) -> bool:
        """Has this hash already been processed (admitted or refused)?"""
        return h in self._seen

    def first_seen(self, h: bytes) -> bool:
        """Mark a hash processed; False if it already was (dedup window)."""
        if h in self._seen:
            return False
        self._seen[h] = None
        if len(self._seen) > self.MAX_TRACKED:
            for k in list(self._seen)[: self.MAX_TRACKED // 2]:
                del self._seen[k]
        return True

    # -- protocol steps --------------------------------------------------

    def announce_targets(self, h: bytes) -> list[str]:
        """Peers to send SeenTx{hash, from=self_url} to: everyone not
        already known to have the content (per-peer have-sets)."""
        have = self._have.get(h, set())
        targets = [u for u in self.peers if u not in have]
        self._bump("seen_sent", len(targets))
        return targets

    def on_seen(self, h: bytes, from_peer: str) -> bool:
        """Inbound SeenTx. True = caller should pull (WantTx) from
        `from_peer`; False = suppressed (we have it, we already processed
        it, or a pull is already outstanding — the announcer is recorded
        as a fallback provider for that pull)."""
        self._bump("seen_recv")
        if from_peer:
            self._note_have(h, from_peer)
        if self.pool.has(h) or h in self._seen:
            self._bump("want_suppressed")
            return False
        if h in self._wanted:
            if from_peer and from_peer not in self._wanted[h]:
                self._wanted[h].append(from_peer)
            self._bump("want_suppressed")
            return False
        self._wanted[h] = []
        self._bump("want_sent")
        return True

    def serve_want(self, h: bytes, to_peer: str = "") -> bytes | None:
        """Inbound WantTx: the Tx delivery (None = we no longer have it —
        committed or evicted between the announce and the pull)."""
        raw = self.pool.get_raw(h)
        if raw is not None:
            self._bump("tx_served")
            self._bump("tx_bytes_sent", len(raw))
            if to_peer:
                self._note_have(h, to_peer)
        return raw

    def on_delivered(self, h: bytes, raw: bytes, from_peer: str) -> None:
        """A pulled (or directly pushed) Tx arrived; caller admits it."""
        self._wanted.pop(h, None)
        self._bump("tx_pulled")
        self._bump("tx_bytes_recv", len(raw))
        if from_peer:
            self._note_have(h, from_peer)

    def pull_failed(self, h: bytes) -> str | None:
        """A WantTx pull errored: next candidate provider, or None (want
        state cleared so a future SeenTx re-triggers the pull)."""
        waiting = self._wanted.get(h)
        if waiting:
            return waiting.pop(0)
        self._wanted.pop(h, None)
        return None

    def forget(self, hashes) -> None:
        """Txs left the pool (committed/expired): drop have/want state so
        the tracking dicts follow pool membership, not chain history."""
        for h in hashes:
            self._have.pop(h, None)
            self._wanted.pop(h, None)
