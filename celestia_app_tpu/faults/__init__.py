"""Deterministic fault injection: named fault points, seeded actions.

The chaos half of the robustness plane. Production code declares *fault
points* — `faults.fire("consensus.post_wal_pre_apply", height=h)` — that
are no-ops until a matching fault is ARMED. Arming happens three ways:

- env: ``CELESTIA_FAULTS`` holds a JSON list of fault specs (or ``@path``
  to a JSON file), read once at import — how chaos tests arm subprocess
  validators at spawn. ``CELESTIA_FAULT_SEED`` seeds the registry rng.
- admin endpoint: ``/faults/*`` on the node HTTP service AND the validator
  consensus service (route_faults below) — how a chaos harness arms a
  crash point on one validator of a LIVE devnet.
- in-process: ``faults.arm(...)`` directly (unit tests, bench --chaos).

Determinism: probabilistic faults draw from ONE ``random.Random(seed)``
owned by the registry, so a fixed seed reproduces the exact trigger
sequence — the property the chaos acceptance tests pin. Every trigger is
counted in telemetry (``faults.<point>.<action>``) and in the spec's own
``triggered`` counter (visible at GET /faults).

Actions:
  drop       caller discards the operation (transport: as if the send
             never happened — the partition primitive)
  delay      fire() sleeps ``delay_s`` before returning (slow network /
             slow disk)
  error      caller raises its domain error (transport: request failed;
             storage: OSError)
  duplicate  caller performs the operation twice (gossip amplification)
  crash      fire() hard-kills the process (``os._exit(137)``) AT the
             fault point — the crash-matrix primitive; recovery is the
             restarted process's problem, which is the point

The fault-point catalog (the names production code fires today):

  net.request                   every outbound peer HTTP request
                                (net/transport.py; ctx: owner, peer, path)
  storage.atomic_write          chain/storage._atomic_write, before the
                                tmp-file write (ctx: path)
  consensus.wal_append          inside ValidatorNode.write_wal, after the
                                fsync'd tmp but BEFORE the rename — a
                                crash here leaves NO durable WAL record
                                (recovery: peer catch-up)
  consensus.post_wal_pre_apply  after the WAL record is durable, before
                                evidence/finalize touch state (recovery:
                                WAL replay)
  consensus.post_apply_pre_latest
                                in ChainDB.save_commit, after the commit
                                artifact is durable but before the LATEST
                                pointer (recovery: resume at height-1,
                                then WAL replay)
  das.serve_sample              das/server.py withholding hook (ctx:
                                height, row, col) — the env-armable twin
                                of SampleCore.withhold()
  statesync.mid_restore         chain/sync.py, after EACH state-sync
                                chunk is durably persisted (ctx: height,
                                index) — a crash here must RESUME,
                                re-fetching only the missing chunks
  statesync.pre_adopt           chain/sync.py, every chunk verified but
                                the snapshot NOT yet adopted (ctx:
                                height) — a restart reuses the full set
  packs.mid_write               das/packs.py, after EACH pack chunk is
                                durably written, before the manifest
                                (ctx: height, data_root, index) — a
                                crash here leaves a manifest-less dir
                                that is never served and gets pruned;
                                the node stays servable via live
                                assembly
  blobpacks.mid_write           das/blob_packs.py, after EACH blob-pack
                                chunk is durably written, before the
                                manifest (ctx: height, data_root,
                                index) — same torn-pack contract as
                                packs.mid_write: never advertised,
                                never served, live /blob/get keeps
                                answering

docs/DESIGN.md "The fault plane" and docs/FORMATS.md §9 are the normative
descriptions of the catalog and the /faults/* admin surface.
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import re
import threading

from celestia_app_tpu.utils import telemetry

ACTIONS = ("drop", "delay", "error", "duplicate", "crash")


@dataclasses.dataclass
class FaultSpec:
    """One armed fault. `point` is matched EXACTLY against the fired
    point name; `match` holds regex filters over the fire() context
    (e.g. {"peer": ":1234", "owner": "val[01]"}) — every filter must
    search-match its context value (a missing context key never
    matches). `count` bounds total triggers (None = unlimited)."""

    fault_id: int
    point: str
    action: str
    prob: float = 1.0
    count: int | None = None
    delay_s: float = 0.05
    match: dict[str, str] = dataclasses.field(default_factory=dict)
    triggered: int = 0

    def matches(self, ctx: dict) -> bool:
        for key, pattern in self.match.items():
            val = ctx.get(key)
            if val is None or not re.search(pattern, str(val)):
                return False
        return True

    def to_json(self) -> dict:
        return {
            "id": self.fault_id,
            "point": self.point,
            "action": self.action,
            "prob": self.prob,
            "count": self.count,
            "delay_s": self.delay_s,
            "match": dict(self.match),
            "triggered": self.triggered,
        }


class FaultRegistry:
    """Process-wide fault-point registry (module singleton below). All
    mutation and firing is lock-guarded: fault points sit on hot
    network/disk paths touched from many threads."""

    def __init__(self, seed: int | None = None):
        self._lock = threading.Lock()
        self._specs: dict[int, FaultSpec] = {}
        self._next_id = 1
        self._rng = random.Random(seed)
        self._fired: dict[str, int] = {}  # per-point trigger counts

    # -- arming -----------------------------------------------------------

    def arm(self, point: str, action: str, *, prob: float = 1.0,
            count: int | None = None, delay_s: float = 0.05,
            match: dict[str, str] | None = None) -> int:
        if action not in ACTIONS:
            raise ValueError(
                f"unknown fault action {action!r}; one of {ACTIONS}"
            )
        if not point:
            raise ValueError("fault point name required")
        # validate match regexes HERE (a 400 at the admin endpoint), not
        # at fire() time — a malformed pattern raising re.error inside a
        # production hot path would kill sender threads, not chaos tests
        for key, pattern in (match or {}).items():
            try:
                re.compile(pattern)
            except re.error as e:
                raise ValueError(
                    f"bad match regex for {key!r}: {e}"
                ) from None
        with self._lock:
            fid = self._next_id
            self._next_id += 1
            self._specs[fid] = FaultSpec(
                fault_id=fid, point=point, action=action,
                prob=float(prob),
                count=None if count is None else int(count),
                delay_s=float(delay_s), match=dict(match or {}),
            )
        return fid

    def disarm(self, fault_id: int | None = None,
               point: str | None = None) -> int:
        """Disarm by id, by point name, or (neither given) everything.
        Returns how many specs were removed."""
        with self._lock:
            if fault_id is not None:
                return 1 if self._specs.pop(int(fault_id), None) else 0
            victims = [
                fid for fid, s in self._specs.items()
                if point is None or s.point == point
            ]
            for fid in victims:
                del self._specs[fid]
            return len(victims)

    def reset(self, seed: int | None = None) -> None:
        """Disarm everything and reseed (chaos-test isolation)."""
        with self._lock:
            self._specs.clear()
            self._rng = random.Random(seed)
            self._fired.clear()

    def reseed(self, seed: int | None) -> None:
        with self._lock:
            self._rng = random.Random(seed)

    # -- firing -----------------------------------------------------------

    def fire(self, point: str, **ctx) -> str | None:
        """Called AT a fault point. Returns the action the caller must
        honor ("drop" / "error" / "duplicate"), or None when no armed
        fault triggers. "delay" faults STACK: every matching delay
        sleeps here (the caller proceeds normally, late) and scanning
        continues, so a standing delay never shadows a later-armed
        terminal fault at the same point; the first matching
        drop/error/duplicate/crash wins. "crash" never returns."""
        # lock-free hot-path exit: a GIL-atomic emptiness read — nothing
        # armed is the overwhelmingly common production state, and every
        # outbound request / WAL append / atomic write across all threads
        # passes through here (worst case: one benignly missed
        # just-armed fault)
        if not self._specs:
            return None
        delay_total = 0.0
        terminal = None
        with self._lock:
            if not self._specs:
                return None
            for s in self._specs.values():
                if s.point != point or not s.matches(ctx):
                    continue
                if s.count is not None and s.triggered >= s.count:
                    continue
                if s.prob < 1.0 and self._rng.random() >= s.prob:
                    continue
                s.triggered += 1
                self._fired[point] = self._fired.get(point, 0) + 1
                if s.action == "delay":
                    delay_total += s.delay_s
                    continue
                terminal = s.action
                break
        if delay_total > 0.0:
            telemetry.incr(f"faults.{point}.delay")
        if terminal is None:
            if delay_total > 0.0:
                import time

                time.sleep(delay_total)
            return None
        telemetry.incr(f"faults.{point}.{terminal}")
        if terminal == "crash":
            from celestia_app_tpu.obs import log as obs_log

            obs_log.get_logger("faults").error(
                f"CRASH at {point}", ctx=str(ctx)
            )
            os._exit(137)
        if delay_total > 0.0:
            import time

            time.sleep(delay_total)
        return terminal

    # -- introspection ----------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "armed": [s.to_json() for s in self._specs.values()],
                "fired": dict(self._fired),
            }

    def armed_count(self) -> int:
        with self._lock:
            return len(self._specs)


# ---------------------------------------------------------------------------
# module singleton + env arming
# ---------------------------------------------------------------------------

REGISTRY = FaultRegistry(
    seed=int(os.environ["CELESTIA_FAULT_SEED"])
    if os.environ.get("CELESTIA_FAULT_SEED") else None
)

arm = REGISTRY.arm
disarm = REGISTRY.disarm
reset = REGISTRY.reset
fire = REGISTRY.fire
snapshot = REGISTRY.snapshot


def arm_from_spec(specs: list[dict], registry: FaultRegistry = REGISTRY,
                  ) -> list[int]:
    """Arm a JSON spec list (the env / admin-endpoint / faults.json
    shape): [{"point": ..., "action": ..., "prob"?, "count"?,
    "delay_s"?, "match"?}, ...]."""
    out = []
    for doc in specs:
        out.append(registry.arm(
            doc["point"], doc["action"],
            prob=doc.get("prob", 1.0),
            count=doc.get("count"),
            delay_s=doc.get("delay_s", 0.05),
            match=doc.get("match"),
        ))
    return out


def arm_from_env(registry: FaultRegistry = REGISTRY) -> int:
    """CELESTIA_FAULTS = JSON list, or @/path/to/specs.json. Malformed
    env is a loud refusal (a chaos run silently not armed would report
    fake resilience), but never fatal to the process."""
    raw = os.environ.get("CELESTIA_FAULTS", "").strip()
    if not raw:
        return 0
    try:
        if raw.startswith("@"):
            with open(raw[1:]) as f:
                specs = json.load(f)
        else:
            specs = json.loads(raw)
        if not isinstance(specs, list):
            raise ValueError("CELESTIA_FAULTS must be a JSON list")
        return len(arm_from_spec(specs, registry))
    except (OSError, ValueError, KeyError, TypeError) as e:
        from celestia_app_tpu.obs import log as obs_log

        obs_log.get_logger("faults").warning(
            "CELESTIA_FAULTS ignored", err=e
        )
        return 0


_ENV_ARMED = arm_from_env()


# ---------------------------------------------------------------------------
# the /faults/* admin surface (one router shared by the node HTTP service
# and the validator consensus service)
# ---------------------------------------------------------------------------


def route_faults(method: str, path: str, payload: dict | None = None) -> dict:
    """Dispatch a /faults request. Raises ValueError on client mistakes
    (the servers map that to 400).

      GET  /faults                 -> {"armed": [...], "fired": {...}}
      POST /faults/arm   {point, action, prob?, count?, delay_s?, match?}
                                   -> {"id": n}
      POST /faults/disarm {id} | {point} | {}   -> {"disarmed": n}
      POST /faults/reset {seed?}   -> {"ok": true}
    """
    payload = payload or {}
    if method == "GET" and path == "/faults":
        return snapshot()
    if method == "POST" and path == "/faults/arm":
        fid = arm_from_spec([payload])[0]
        return {"id": fid}
    if method == "POST" and path == "/faults/disarm":
        n = disarm(fault_id=payload.get("id"), point=payload.get("point"))
        return {"disarmed": n}
    if method == "POST" and path == "/faults/reset":
        REGISTRY.reset(seed=payload.get("seed"))
        return {"ok": True}
    raise ValueError(f"no fault route {method} {path}")
