"""Benchmark: full 128×128 block extend+commit, device vs CPU baseline.

Measures the flagship device program (da/eds.py: 2D GF(256) RS extension +
4k NMT axis roots + data root — the reference's `da.ExtendShares` +
`DAH.Hash()` chain, pkg/da/data_availability_header.go:65-108) on the default
JAX backend, and reports speedup vs the strongest CPU implementation in-tree
(utils/fast_host: BLAS bit-matmul RS + OpenSSL SHA-256). The reference's own
Go path cannot run here (no Go toolchain); fast_host is our measured stand-in
for BASELINE.md config 0, cached in bench_baseline.json.

Prints ONE JSON line:
  {"metric": "extend_commit_128_ms", "value": <device ms/block>,
   "unit": "ms", "vs_baseline": <cpu_ms / device_ms>}
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

K = 128
BASELINE_FILE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "bench_baseline.json")


def _bench_ods(k: int) -> np.ndarray:
    rng = np.random.default_rng(0)
    ods = rng.integers(0, 256, size=(k, k, 512), dtype=np.uint8)
    ods[..., :29] = 0
    ods[..., 28] = 7  # one user namespace, sorted layout
    return ods


def measure_baseline() -> float:
    """CPU fast-host pipeline, ms/block (one untimed warmup, best of 2)."""
    from celestia_app_tpu.ops import leopard
    from celestia_app_tpu.utils import fast_host

    ods = _bench_ods(K)
    leopard.bit_matrix(K)  # warm the cached generator matrix off the clock
    times = []
    for _ in range(2):
        t0 = time.perf_counter()
        eds = fast_host.extend_square_fast(ods)
        fast_host.axis_roots_fast(eds)
        times.append(time.perf_counter() - t0)
    return min(times) * 1000.0


def measure_device(reps: int = 10) -> float:
    import jax

    from celestia_app_tpu.da import eds as eds_mod

    run = eds_mod.jitted_pipeline(K)
    ods = jax.device_put(_bench_ods(K))
    jax.block_until_ready(run(ods))  # compile + warm
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(run(ods))
        times.append(time.perf_counter() - t0)
    return float(np.median(times)) * 1000.0


def main() -> None:
    if "--measure-baseline" in sys.argv:
        ms = measure_baseline()
        with open(BASELINE_FILE, "w") as f:
            json.dump(
                {
                    "metric": "extend_commit_128_ms",
                    "cpu_ms": ms,
                    "impl": "utils/fast_host (numpy BLAS bit-matmul RS + "
                            "hashlib SHA-256)",
                },
                f,
                indent=2,
            )
            f.write("\n")
        print(f"baseline measured: {ms:.1f} ms -> {BASELINE_FILE}",
              file=sys.stderr)
        return

    if os.path.exists(BASELINE_FILE):
        with open(BASELINE_FILE) as f:
            cpu_ms = json.load(f)["cpu_ms"]
    else:
        cpu_ms = measure_baseline()

    device_ms = measure_device()
    print(
        json.dumps(
            {
                "metric": "extend_commit_128_ms",
                "value": round(device_ms, 3),
                "unit": "ms",
                "vs_baseline": round(cpu_ms / device_ms, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
