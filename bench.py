"""Benchmark: full 128×128 block extend+commit, device vs CPU baseline.

Measures the flagship device program (da/eds.py: 2D GF(256) RS extension +
4k NMT axis roots + data root — the reference's `da.ExtendShares` +
`DAH.Hash()` chain, pkg/da/data_availability_header.go:65-108) on the default
JAX backend, and reports speedup vs the strongest CPU implementation in-tree
(native/baseline_pipeline.cc: AVX2 leopard-FFT RS encode + SHA-NI hashing —
the same per-core techniques the reference's Go stack uses). The reference's
own Go binary cannot be built here (no Go toolchain); the native pipeline is
the measured stand-in for BASELINE.md config 0, cached in bench_baseline.json,
and its data root is asserted bit-identical to this framework's pipelines.

Prints ONE JSON line:
  {"metric": "extend_commit_128_ms", "value": <device ms/block>,
   "unit": "ms", "vs_baseline": <cpu_ms / device_ms>}

Resilience (round-2: relay refused to init; round-3: relay HUNG and the
driver's kill landed before any JSON was printed): the default mode runs a
deadline-driven loop bounded by TOTAL_BUDGET_S — fast liveness probes gate
each full measurement attempt (a hung relay costs 90 s, not 900), children
re-exec in clean runtimes, and a provisional failure-JSON line is flushed to
stdout before every wait, so killing this process at ANY instant still
leaves a parseable last line for the driver.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

K = 128
BASELINE_FILE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "bench_baseline.json")
# Round-3 postmortem: the driver killed the whole bench at some point after
# attempt 1's 900 s timeout (rc=124, no JSON line on stdout). Two rules now:
# (1) ALL waiting fits inside a hard TOTAL budget chosen to sit safely under
# the driver's observed window, and (2) a provisional failure-JSON line is
# flushed to stdout at start and after EVERY state change, so the driver's
# axe can fall at any instant and still find a parseable last line.
TOTAL_BUDGET_S = float(os.environ.get("CELESTIA_BENCH_BUDGET_S", 780))
PROBE_TIMEOUT_S = 90      # relay liveness probe (hang == relay down)
# one full measurement child; capped so that one failed full attempt still
# leaves room for a second, calibration-skipping attempt inside the budget
ATTEMPT_TIMEOUT_S = 420
SAFETY_MARGIN_S = 45      # reserve to emit the final JSON before the axe


def _bench_ods(k: int) -> np.ndarray:
    rng = np.random.default_rng(0)
    ods = rng.integers(0, 256, size=(k, k, 512), dtype=np.uint8)
    ods[..., :29] = 0
    ods[..., 28] = 7  # one user namespace, sorted layout
    return ods


def measure_baseline() -> tuple[float, str, str]:
    """Reference-class CPU pipeline: (ms, data_root_hex, methodology).

    Primary: the native C++ implementation (native/baseline_pipeline.cc —
    leopard-style AVX2 GF(2^8) FFT encode + SHA-NI NMT/Merkle hashing, the
    same techniques the reference's Go stack leans on via klauspost
    reedsolomon and crypto/sha256; single-threaded on this 1-vCPU machine,
    where the reference e2e benches use 8 CPUs). Falls back to the in-tree
    numpy/hashlib pipeline if the native build is unavailable.
    """
    from celestia_app_tpu.utils import native_baseline

    try:
        j = native_baseline.run(_bench_ods(K), reps=3)
        return (
            float(j["cpu_ms"]),
            j["data_root"],
            "native/baseline_pipeline.cc (AVX2 leopard-FFT RS + SHA-NI "
            "NMT/Merkle, 1 thread)",
        )
    except Exception as e:
        print(f"native baseline unavailable ({type(e).__name__}: {e}); "
              "falling back to numpy/hashlib fast_host", file=sys.stderr)
        from celestia_app_tpu.ops import leopard
        from celestia_app_tpu.utils import fast_host

        ods = _bench_ods(K)
        leopard.bit_matrix(K)
        times = []
        for _ in range(2):
            t0 = time.perf_counter()
            eds = fast_host.extend_square_fast(ods)
            fast_host.axis_roots_fast(eds)
            times.append(time.perf_counter() - t0)
        return (
            min(times) * 1000.0,
            "",
            "utils/fast_host (numpy BLAS bit-matmul RS + hashlib SHA-256)",
        )


def _slope_ns() -> tuple[int, int]:
    """Loop lengths for slope timing: long enough on accelerators to drown
    per-dispatch overhead, short on the CPU fallback where one block is
    seconds."""
    import jax

    if jax.devices()[0].platform == "cpu":
        return 1, 3
    return 4, 20


def _time_fn(run, ods, reps: int, fold=None) -> float:
    """Per-block ms as the SLOPE between an n_small- and an n_large-iteration
    device loop, each ended by a scalar host fetch.

    Round-4 finding: on the axon TPU relay `jax.block_until_ready` returns
    immediately (dispatch is acknowledged, not completed), so per-call wall
    timing measures tunnel round-trips (~70-90 ms), not compute — every
    hardware number from rounds 1-3 was relay latency. Chaining the work
    n times inside ONE jitted fori_loop (the output of block i feeds block
    i+1, so nothing dead-code-eliminates) and fetching a 4-byte checksum
    gives t(n) = overhead + n*per_block; the slope cancels fetch latency,
    dispatch cost, and any async-queue artifacts on every backend.
    """
    import jax
    import jax.numpy as jnp

    if fold is None:
        def fold(c, y):
            # default: outputs are (eds, row_roots, col_roots, data_root);
            # the 32-byte root transitively depends on every EDS byte
            return c.at[0, 0, :32].set(c[0, 0, :32] ^ y[3])

    @jax.jit
    def loop(x, n):
        def body(i, c):
            return fold(c, run(c))

        c = jax.lax.fori_loop(0, n, body, x)
        return jnp.sum(c.astype(jnp.int32))

    n_small, n_large = _slope_ns()
    # compile once (dynamic trip count), warm both lengths
    np.asarray(loop(ods, n_small))
    np.asarray(loop(ods, n_large))

    def best(n: int) -> float:
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            np.asarray(loop(ods, n))
            ts.append(time.perf_counter() - t0)
        return min(ts)

    slope = (best(n_large) - best(n_small)) / (n_large - n_small) * 1000.0
    # Tunnel jitter can make best(n_large) <= best(n_small) for very fast
    # fns; floor at a small positive value so callers never divide by zero
    # and a noise-zero probe cannot falsely win calibration's min().
    return max(slope, 0.05)


def _fold_extend(k: int):
    """Carry-fold for extend-only timing: xor all three parity quadrants
    back into the carry so each pass's full output stays live."""

    def fold(c, y):
        return c ^ y[:k, k:, :] ^ y[k:, :k, :] ^ y[k:, k:, :]

    return fold


def _check_baseline_root(root: bytes) -> None:
    """Loudly flag device/native divergence: the docstring's bit-compat claim
    is enforced here for every bench run that has a recorded baseline root."""
    if not os.path.exists(BASELINE_FILE):
        return
    with open(BASELINE_FILE) as f:
        base_root = json.load(f).get("data_root", "")
    if base_root and base_root != root.hex():
        global _ROOT_MISMATCH
        _ROOT_MISMATCH = True
        print("WARNING: device data root differs from native baseline root "
              f"({root.hex()[:16]} vs {base_root[:16]})", file=sys.stderr)


_ROOT_MISMATCH = False


def measure_device(reps: int = 5) -> tuple[float, str]:
    """Device pipeline (ms/block, sha_impl). The SHA-256 stage uses the
    Pallas register kernel by default on accelerators; if that fails to
    compile on the current toolchain, fall back to the jnp scan path and
    still report."""
    import jax

    from celestia_app_tpu.da import eds as eds_mod

    from celestia_app_tpu.ops import sha256 as sha_mod

    ods = jax.device_put(_bench_ods(K))
    if not sha_mod.use_pallas():
        ms = _time_fn(eds_mod.jitted_pipeline(K), ods, reps)
        root = bytes(np.asarray(eds_mod.jitted_pipeline(K)(ods)[3]))
        _check_baseline_root(root)
        return ms, "jnp"
    try:
        pallas_ms = _time_fn(eds_mod.jitted_pipeline(K), ods, reps)
        root_pallas = bytes(np.asarray(eds_mod.jitted_pipeline(K)(ods)[3]))
    except Exception as e:  # Pallas lowering/compile failure: degrade, don't die
        print(f"pallas path failed ({type(e).__name__}: {e}); "
              "retrying with CELESTIA_SHA256_IMPL=jnp", file=sys.stderr)
        pallas_ms, root_pallas = None, None
    # Cross-check the kernel against the jnp scan path before trusting it.
    saved = os.environ.get("CELESTIA_SHA256_IMPL")
    os.environ["CELESTIA_SHA256_IMPL"] = "jnp"
    try:
        eds_mod.jitted_pipeline.cache_clear()
        jnp_pipeline = eds_mod.jitted_pipeline(K)
        root_jnp = bytes(np.asarray(jnp_pipeline(ods)[3]))
        _check_baseline_root(root_jnp)
        if root_pallas == root_jnp:
            return pallas_ms, "pallas"
        if root_pallas is not None:
            print("pallas/jnp data-root MISMATCH; reporting jnp path",
                  file=sys.stderr)
        return _time_fn(jnp_pipeline, ods, reps), "jnp"
    finally:
        if saved is None:
            os.environ.pop("CELESTIA_SHA256_IMPL", None)
        else:
            os.environ["CELESTIA_SHA256_IMPL"] = saved
        eds_mod.jitted_pipeline.cache_clear()


def _probe_rs_schedules(ods, reps: int,
                        budget_s: float | None = None) -> dict[str, float]:
    """Time every (layout × dtype) RS schedule; shared by --stages and the
    child's calibration so the grid cannot drift between them.

    `budget_s` bounds total probing wall-clock (each first compile costs
    20-40 s on TPU; seven schedules could eat the whole attempt window):
    schedules are probed in priority order — round-4 slope timing on real
    silicon measured the fused Pallas pass at 2.7 ms vs 6.7 (batched/int8)
    and 4.7 (flat/bf16), so Pallas goes right after its cross-check
    reference — and probing stops when the budget is spent, keeping
    whatever was measured."""
    import jax

    from celestia_app_tpu.ops import rs

    t_start = time.monotonic()

    def over_budget() -> bool:
        return (budget_s is not None
                and time.monotonic() - t_start > budget_s)

    probes = {}
    fns = {}

    fold = _fold_extend(K)

    def probe_xla(layout: str, dtype: str) -> None:
        try:
            fn = jax.jit(rs.extend_square_fn(K, layout=layout, dtype=dtype))
            fns[f"{layout}/{dtype}"] = fn
            probes[f"{layout}/{dtype}"] = _time_fn(fn, ods, reps, fold=fold)
        except Exception as e:
            print(f"rs probe {layout}/{dtype} failed: {e}", file=sys.stderr)

    def probe_pallas() -> None:
        try:
            # the fused Pallas pass (unpack+matmul+pack in VMEM); fails
            # cleanly where Pallas cannot lower (e.g. CPU backend)
            fn = jax.jit(rs.extend_square_fn(K, layout="pallas"))
            ms = _time_fn(fn, ods, reps, fold=fold)
            # trust only a bit-identical kernel (cross-check vs the
            # compiled XLA reference probed just before)
            ref = fns.get("batched/int8") or fns.get("flat/int8")
            if ref is None:
                print("rs probe pallas/bf16: no XLA reference compiled; "
                      "result untrusted, discarded", file=sys.stderr)
            elif bool((fn(ods) == ref(ods)).all()):
                probes["pallas/bf16"] = ms
            else:
                print("rs probe pallas/bf16 MISMATCH vs XLA path; discarded",
                      file=sys.stderr)
        except Exception as e:
            print(f"rs probe pallas/bf16 failed: {e}", file=sys.stderr)

    # priority order: the cross-check reference first, then the fused
    # Pallas candidate (round-4 silicon winner at 2.7 ms), then the rest
    plan = [lambda: probe_xla("batched", "int8"),
            probe_pallas,
            lambda: probe_xla("flat", "bf16"),
            lambda: probe_xla("fused", "int8"),
            lambda: probe_xla("batched", "bf16"),
            lambda: probe_xla("flat", "int8"),
            lambda: probe_xla("fused", "bf16")]
    for i, step in enumerate(plan):
        if over_budget():
            print(f"rs probe budget spent after {i} schedules",
                  file=sys.stderr)
            break
        step()
    return probes


def measure_stages(reps: int = 10) -> None:
    """Report per-stage device timings to stderr (--stages), including the
    full RS schedule grid so the faster schedule on the actual hardware is
    visible."""
    import jax

    from celestia_app_tpu.da import eds as eds_mod
    from celestia_app_tpu.ops import rs

    ods = jax.device_put(_bench_ods(K))
    probes = _probe_rs_schedules(ods, reps)
    # attribute against the schedule the PIPELINE actually uses (env-driven)
    active = f"{rs._rs_layout()}/{rs._rs_dtype()}"
    extend_ms = probes.get(active, next(iter(probes.values())))
    try:
        full_ms = _time_fn(eds_mod.jitted_pipeline(K), ods, reps)
    except Exception as e:
        print(f"pallas path failed in --stages ({type(e).__name__}); "
              "using jnp", file=sys.stderr)
        os.environ["CELESTIA_SHA256_IMPL"] = "jnp"
        eds_mod.jitted_pipeline.cache_clear()
        full_ms = _time_fn(eds_mod.jitted_pipeline(K), ods, reps)

    # NMT+root stage ≈ full − extend (stages fuse inside one dispatch, so
    # subtraction is the honest attribution available without a profiler).
    probe_str = ", ".join(f"extend({k})={v:.2f} ms" for k, v in probes.items())
    print(
        f"stages: {probe_str}, full[{active}]={full_ms:.2f} ms, "
        f"nmt+root≈{full_ms - extend_ms:.2f} ms",
        file=sys.stderr,
    )


def measure_codec(ks=None) -> None:
    """Codec-plane bench (--codec): every REGISTERED DA commitment
    scheme head to head — 2D-RS+NMT (wire id 0), the CMT (1), the
    polar-coded PCMT (2) — per cost that matters at millions of
    sampling light clients. One BENCH JSON line:

      {"metric": "codec_head_to_head", "k": {"32": {scheme: {...}}, ...}}

    Per scheme at each k: `encode_ms` (one full commit dispatch, warm
    best-of-reps), `proof_bytes_per_sample` (EXACT canonical wire bytes
    of one sample proof, FORMATS §16.3/§16.6 — not JSON/base64
    inflation), `hashes_per_sample_verify` (sha256 invocations a
    verifier pays), `samples_to_99_confidence` (the scheme's own catch
    probability — 2D-RS's combinatorial 1/4 vs the coded-tree schemes'
    measured peeling thresholds), `commitment_bytes` (the once-per-
    block download: 4k NMT roots vs each tree's root hash list),
    `repair_ms` (reconstruction from a 1/4-erased block),
    `fraud_proof_bytes` + `fraud_verify_ms` (a BEFP's k shares vs ONE
    parity equation for cmt/pcmt — the three-way the PCMT exists for:
    it wins fraud-proof and commitment size, and PAYS for it in
    per-sample bytes and hash count; the bench reports the trade, not
    a winner). The acceptance gate — the paper's headline — stays CMT
    `proof_bytes_per_sample` strictly below 2D-RS at k=128.

    A second BENCH line, `rs_tunable_sweep`, sweeps the tunable-rate RS
    knob (ops/rs_tunable.py, arXiv:2201.08261): closed-form analytics
    plus a measured host-engine encode per in-field (k, n) point;
    combos past the GF(256) point budget are SKIPPED AND LOGGED, never
    silently dropped. Backend labeling per FORMATS §12.2
    (`"backend": "cpu-fallback"`).
    """
    import jax

    from celestia_app_tpu.da import codec as dacodec
    from celestia_app_tpu.testing import malicious

    if ks is None:
        ks = tuple(int(x) for x in os.environ.get(
            "CELESTIA_BENCH_CODEC_K", "32,128").split(","))
    reps = int(os.environ.get("CELESTIA_BENCH_CODEC_REPS", "3"))
    backend = jax.devices()[0].platform
    if backend == "cpu":
        backend = "cpu-fallback"
    out: dict = {}
    for k in ks:
        ods = _bench_ods(k)
        per_k: dict = {}
        for sid in dacodec.registered_ids():
            codec = dacodec.by_id(sid)
            name = codec.name
            entry = codec.compute_entry(ods)  # warm (jit compiles)
            encode_ms = None
            for _ in range(reps):
                t0 = time.perf_counter()
                codec.compute_entry(ods)
                dt = (time.perf_counter() - t0) * 1e3
                encode_ms = dt if encode_ms is None else min(encode_ms, dt)
            doc = codec.commitments_doc(entry)
            comm = codec.commitments_from_doc(doc, entry.data_root.hex(),
                                              k)
            space = codec.sample_space(comm)
            cell = space[len(space) // 3]
            sample_doc = codec.open_sample(entry, cell)
            assert codec.verify_sample(comm, sample_doc) is not None
            proof_bytes = codec.sample_wire_bytes(sample_doc, comm)
            commitment_bytes = (
                sum(len(h) for h in comm.root_hashes)
                if hasattr(comm, "root_hashes")  # cmt + pcmt
                else sum(len(r) for r in comm.row_roots)
                + sum(len(r) for r in comm.col_roots))
            # repair from a 1/4-erased block (seeded mask; the CMT seed
            # is pinned inside its peeling threshold — see ops/ldpc.py)
            rng = np.random.default_rng(1)
            n = len(space)
            drop = set(
                int(i) for i in rng.choice(n, size=n // 4, replace=False)
            )
            samples = {}
            for i, c in enumerate(space):
                if i not in drop:
                    d = codec.open_sample(entry, c)
                    got = codec.verify_sample(comm, d)
                    samples[c] = got[1]
            t0 = time.perf_counter()
            rec = codec.repair(comm, samples)
            repair_ms = (time.perf_counter() - t0) * 1e3
            assert np.array_equal(np.asarray(rec), ods)
            # incorrect-coding fraud: commit a corrupt symbol, prove it
            # (THE shared fixture, testing/malicious.py — same one the
            # conformance suite and the scenario matrix drive)
            bad, location, _withheld, _wire = \
                malicious.incorrect_coding_fixture(name, ods)
            bad_comm = bad.dah
            fp = codec.build_fraud_proof(bad, location)
            assert codec.verify_fraud_proof(bad_comm, fp) is True
            if hasattr(fp, "members"):  # one equation, cmt + pcmt
                fraud_bytes = sum(
                    codec.sample_wire_bytes(m.doc, bad_comm)
                    for m in fp.members)
            else:
                from celestia_app_tpu import appconsts

                fraud_bytes = sum(
                    len(s.share)
                    + len(s.proof.nodes) * appconsts.NMT_ROOT_SIZE
                    for s in fp.shares)
            fraud_ms = None
            for _ in range(reps):
                t0 = time.perf_counter()
                assert codec.verify_fraud_proof(bad_comm, fp) is True
                dt = (time.perf_counter() - t0) * 1e3
                fraud_ms = dt if fraud_ms is None else min(fraud_ms, dt)
            per_k[name] = {
                "encode_ms": round(encode_ms, 3),
                "proof_bytes_per_sample": proof_bytes,
                "hashes_per_sample_verify":
                    codec.hashes_per_sample_verify(comm),
                "samples_to_99_confidence":
                    codec.samples_for_confidence(0.99),
                "catch_probability": codec.catch_probability(),
                "commitment_bytes": commitment_bytes,
                "repair_ms": round(repair_ms, 3),
                "fraud_proof_bytes": fraud_bytes,
                "fraud_verify_ms": round(fraud_ms, 3),
            }
        out[str(k)] = per_k
    headline = None
    if "128" in out:
        headline = (out["128"]["cmt-ldpc"]["proof_bytes_per_sample"]
                    < out["128"]["rs2d-nmt"]["proof_bytes_per_sample"])
    print(json.dumps({
        "metric": "codec_head_to_head",
        "backend": backend,
        "k": out,
        "cmt_proof_smaller_at_128": headline,
    }))
    _measure_rs_tunable_sweep(backend)


def _measure_rs_tunable_sweep(backend: str) -> None:
    """The tunable-rate RS knob (ops/rs_tunable.py): per swept
    extension factor, the closed-form protocol analytics plus a
    measured host-engine 2D encode (the analytics are exact; only the
    encode wall time is hardware). FORMATS §16.7 pins the line."""
    from celestia_app_tpu.ops import rs_tunable

    k = int(os.environ.get("CELESTIA_BENCH_RS_SWEEP_K", "32"))
    factors = tuple(float(f) for f in os.environ.get(
        "CELESTIA_BENCH_RS_SWEEP_FACTORS", "1.25,1.5,2.0,3.0,9.0"
    ).split(","))
    ods = _bench_ods(k)
    points, skipped = [], []
    for f in factors:
        n = round(k * f)
        try:
            point = rs_tunable.analytics(k, n, n)
        except ValueError as e:
            # no silent caps: a factor past the GF(256) point budget is
            # reported as skipped, with the reason
            skipped.append({"factor": f, "n": n, "reason": str(e)})
            continue
        t0 = time.perf_counter()
        rect = rs_tunable.extend_2d(ods, n, n, "host")
        point["encode_ms"] = round((time.perf_counter() - t0) * 1e3, 3)
        point["factor"] = f
        assert rect.shape[0] == n and rect.shape[1] == n
        points.append(point)
    print(json.dumps({
        "metric": "rs_tunable_sweep",
        "backend": backend,
        "k": k,
        "points": points,
        "skipped": skipped,
    }))


def measure_proofs(n_proofs: int = 10_000) -> None:
    """BASELINE config 3: batched share-proof generation, proofs/sec.

    Builds the 128x128 block's row trees in one device pass
    (da/proof_device.BlockProver), then times assembling n_proofs share
    proofs (pure index arithmetic per proof). Prints its own JSON line;
    the driver's headline metric remains the default mode.
    """
    from celestia_app_tpu.da import dah as dah_mod
    from celestia_app_tpu.da import proof_device

    ods = _bench_ods(K)
    d, eds_obj, _ = dah_mod.new_dah_from_ods(ods)
    t0 = time.perf_counter()
    prover = proof_device.BlockProver(eds_obj, d)
    build_ms = (time.perf_counter() - t0) * 1000
    rng = np.random.default_rng(0)
    starts = rng.integers(0, K * K - 4, n_proofs)
    ns = bytes(29)
    t0 = time.perf_counter()
    for s0 in starts:
        prover.prove_shares(int(s0), int(s0) + 4, ns)
    dt = time.perf_counter() - t0
    print(
        json.dumps(
            {
                "metric": "share_proofs_per_sec_128",
                "value": round(n_proofs / dt, 1),
                "unit": "proofs/s",
                "tree_build_ms": round(build_ms, 1),
            }
        )
    )


def _calibrate_rs_schedule() -> str:
    """Probe the four (layout × dtype) RS schedules briefly and pin the
    fastest via env BEFORE the pipeline traces — all four are bit-identical
    (tests/test_rs.py), so this is pure schedule selection on the actual
    hardware the measurement runs on."""
    import jax

    ods = jax.device_put(_bench_ods(K))
    # half the ACTUAL attempt window (parent passes it down; a shortened
    # attempt shortens calibration with it), leaving the rest for the
    # full-pipeline compile + measurement
    window = float(os.environ.get("CELESTIA_BENCH_CHILD_TIMEOUT",
                                  ATTEMPT_TIMEOUT_S))
    probes = _probe_rs_schedules(ods, reps=3, budget_s=window / 2)
    for name, ms in probes.items():
        print(f"rs probe {name}: {ms:.1f} ms", file=sys.stderr)
    if not probes:
        return "batched/int8"
    best = min(probes, key=probes.get)
    layout, dtype = best.split("/")
    os.environ["CELESTIA_RS_LAYOUT"] = layout
    os.environ["CELESTIA_RS_DTYPE"] = dtype
    return best


def _run_child() -> None:
    """One measurement attempt in THIS process (spawned by the parent)."""
    if os.path.exists(BASELINE_FILE):
        with open(BASELINE_FILE) as f:
            cpu_ms = json.load(f)["cpu_ms"]
    else:
        cpu_ms, _, _ = measure_baseline()

    if os.environ.get("CELESTIA_BENCH_MINIMAL"):
        # Shortest possible path to a silicon number for a relay window
        # that may close in minutes: default schedule, jnp SHA (ONE
        # pipeline compile, no Pallas attempt / cross-check), few reps.
        # The richer modes below re-measure properly once a window holds.
        import jax

        from celestia_app_tpu.da import eds as eds_mod

        os.environ["CELESTIA_SHA256_IMPL"] = "jnp"
        eds_mod.jitted_pipeline.cache_clear()
        ods = jax.device_put(_bench_ods(K))
        pipeline = eds_mod.jitted_pipeline(K)
        device_ms = _time_fn(pipeline, ods, reps=3)
        _check_baseline_root(bytes(np.asarray(pipeline(ods)[3])))
        from celestia_app_tpu.ops import rs

        out = {
            "metric": "extend_commit_128_ms",
            "value": round(device_ms, 3),
            "unit": "ms",
            "vs_baseline": round(cpu_ms / device_ms, 2),
            "sha_impl": "jnp",
            "rs_schedule": f"{rs._rs_layout()}/{rs._rs_dtype()} (minimal mode)",
            "backend": jax.devices()[0].platform,
        }
        if _ROOT_MISMATCH:
            out["baseline_root_match"] = False
        print(json.dumps(out))
        return

    if os.environ.get("CELESTIA_BENCH_SKIP_CAL"):
        # parent is low on budget: trust env/defaults rather than probing
        rs_schedule = (f"{os.environ.get('CELESTIA_RS_LAYOUT', 'batched')}/"
                       f"{os.environ.get('CELESTIA_RS_DTYPE', 'int8')} (uncalibrated)")
    else:
        rs_schedule = _calibrate_rs_schedule()
    try:
        device_ms, sha_impl = measure_device()
    except Exception as e:
        # the winning probe compiled standalone but broke the FULL pipeline
        # (e.g. VMEM pressure once fused with the NMT stage): fall back to
        # the default schedule instead of burning the parent's retries
        print(f"pipeline failed under schedule {rs_schedule} "
              f"({type(e).__name__}: {e}); retrying with defaults",
              file=sys.stderr)
        os.environ.pop("CELESTIA_RS_LAYOUT", None)
        os.environ.pop("CELESTIA_RS_DTYPE", None)
        rs_schedule = "batched/int8 (fallback)"
        from celestia_app_tpu.da import eds as eds_mod

        eds_mod.jitted_pipeline.cache_clear()
        device_ms, sha_impl = measure_device()
    import jax

    out = {
        "metric": "extend_commit_128_ms",
        "value": round(device_ms, 3),
        "unit": "ms",
        "vs_baseline": round(cpu_ms / device_ms, 2),
        "sha_impl": sha_impl,
        "rs_schedule": rs_schedule,
        "backend": jax.devices()[0].platform,
    }
    if _ROOT_MISMATCH:
        out["baseline_root_match"] = False
    print(json.dumps(out))


def _parse_last_json(text: str):
    for line in reversed(text.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


def _emit(errors: list[str], note: str) -> None:
    """Flush a provisional failure-JSON line to stdout NOW. The driver parses
    the last JSON line of whatever stdout it captured, so as long as one of
    these precedes every long wait, a mid-wait kill still yields a structured
    record instead of round 3's parsed=null."""
    line = {
        "metric": "extend_commit_128_ms",
        "value": None,
        "unit": "ms",
        "error": ("; ".join(errors + [note]))[-2000:],
    }
    # A dead relay should not erase history: attach the last measurement
    # the watcher/bench landed on real hardware (clearly labeled with its
    # own provenance — `value` above stays null because THIS run measured
    # nothing).
    hw_file = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "BENCH_HW_r4.json")
    try:
        with open(hw_file) as f:
            hw = json.load(f)
        if isinstance(hw, dict) and hw.get("value") is not None:
            line["last_hw_result"] = {
                k: hw[k] for k in
                ("metric", "value", "unit", "vs_baseline", "rs_schedule",
                 "backend") if k in hw
            }
            line["last_hw_result"]["source"] = "BENCH_HW_r4.json"
    except Exception:
        # nothing may stop the provisional line from printing — this
        # history attachment is strictly best-effort
        pass
    print(json.dumps(line), flush=True)


def _run_probe_child(timeout_s: float) -> str | None:
    """Fast relay-liveness probe in a child: returns None if the backend
    initializes and a device round-trip works, else a one-line error. A HUNG
    relay (the round-3 mode: connect blocks forever, no error) costs
    PROBE_TIMEOUT_S here instead of a full attempt timeout."""
    code = (
        "import jax, numpy as np\n"
        "x = jax.device_put(np.ones((8, 8), np.float32))\n"
        "assert float(x.sum()) == 64.0\n"
        "print('PROBE_OK', jax.devices()[0].platform)\n"
    )
    try:
        r = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=timeout_s,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        return f"probe hung ({timeout_s:.0f}s) — relay down"
    if r.returncode == 0 and "PROBE_OK" in r.stdout:
        return None
    tail = (r.stderr or "").strip().splitlines()
    return f"probe rc={r.returncode}: " + " | ".join(tail[-2:])


def _run_cpu_fallback(errors: list[str], deadline: float) -> bool:
    """Relay down: measure on the CPU backend NOW and emit the result
    with `"backend": "cpu-fallback"` — a labeled real number keeps the
    perf trajectory continuous instead of burning the whole budget
    polling a dead tunnel (the relay has answered no probes since round
    4). True = a final JSON line was emitted."""
    # this is the bench's LAST act (the alternative is polling a dead
    # tunnel), so the child gets the whole remaining budget, not the
    # per-attempt cap: minimal mode on one CPU core runs ~7 min
    remaining = deadline - time.monotonic()
    child_timeout = remaining - SAFETY_MARGIN_S
    if child_timeout < 120:
        return False
    env = dict(os.environ)
    # the axon sitecustomize registers its plugin whenever the pool var
    # is set, and a DOWN relay hangs ANY backend init — even cpu — so
    # the fallback child must not see it at all
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["CELESTIA_BENCH_CHILD_TIMEOUT"] = str(int(child_timeout))
    env["CELESTIA_BENCH_MINIMAL"] = "1"   # shortest path to a real number
    env["CELESTIA_BENCH_SKIP_CAL"] = "1"  # schedule probing is relay-side noise
    _emit(errors, "provisional: relay down, measuring labeled CPU fallback")
    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child"],
            capture_output=True,
            text=True,
            timeout=child_timeout,
            env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        errors.append(f"cpu fallback: timeout after {child_timeout:.0f}s")
        _emit(errors, "provisional: cpu fallback timed out")
        return False
    parsed = _parse_last_json(r.stdout) if r.returncode == 0 else None
    if parsed is None or parsed.get("value") is None:
        tail = (r.stderr or "").strip().splitlines()
        errors.append(
            f"cpu fallback rc={r.returncode}: " + " | ".join(tail[-2:]))
        _emit(errors, "provisional: cpu fallback failed")
        return False
    parsed["backend"] = "cpu-fallback"
    parsed["relay_error"] = errors[-1] if errors else ""
    print(json.dumps(parsed), flush=True)
    return True


def _run_parent() -> None:
    """Deadline-driven measurement loop. Invariants: (a) total wall-clock is
    bounded by TOTAL_BUDGET_S regardless of how attempts fail, (b) stdout
    always ends with a parseable JSON line, even if the driver kills us
    mid-attempt (provisional lines are flushed before every wait), and
    (c) a dead relay FAILS FAST: one confirming re-probe, then a labeled
    CPU-fallback measurement instead of polling until the budget dies."""
    deadline = time.monotonic() + TOTAL_BUDGET_S
    errors: list[str] = []
    _emit(errors, "provisional: bench starting")
    attempt = 0
    while True:
        remaining = deadline - time.monotonic()
        if remaining < PROBE_TIMEOUT_S + SAFETY_MARGIN_S:
            _emit(errors, f"budget exhausted after {attempt} attempt(s)")
            return
        probe_err = _run_probe_child(min(PROBE_TIMEOUT_S, remaining / 2))
        if probe_err is not None:
            errors = errors[-6:]
            errors.append(probe_err)
            # relay-probe housekeeping (ROADMAP): confirm with one SHORT
            # re-probe (rules out a transient), then fall back to a
            # labeled CPU number rather than waiting out the budget
            second = _run_probe_child(
                min(PROBE_TIMEOUT_S / 3,
                    max(10.0, (deadline - time.monotonic()) / 4)))
            if second is not None:
                errors.append(second)
                if _run_cpu_fallback(errors, deadline):
                    return
            _emit(errors, "provisional: waiting for relay")
            time.sleep(min(20, max(0, deadline - time.monotonic() - SAFETY_MARGIN_S)))
            continue
        attempt += 1
        remaining = deadline - time.monotonic()
        child_timeout = min(ATTEMPT_TIMEOUT_S, remaining - SAFETY_MARGIN_S)
        if child_timeout < 120:
            _emit(errors, "budget too low for a measurement attempt")
            return
        env = dict(os.environ)
        env["CELESTIA_BENCH_CHILD_TIMEOUT"] = str(int(child_timeout))
        if child_timeout < 300:
            # not enough time for the full schedule calibration: measure with
            # the default (or previously pinned) schedule only
            env["CELESTIA_BENCH_SKIP_CAL"] = "1"
        _emit(errors, f"provisional: attempt {attempt} running")
        try:
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--child"],
                capture_output=True,
                text=True,
                timeout=child_timeout,
                env=env,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            )
        except subprocess.TimeoutExpired:
            errors.append(f"attempt {attempt}: timeout after {child_timeout:.0f}s")
            _emit(errors, "provisional: attempt timed out")
            continue
        if r.returncode == 0:
            parsed = _parse_last_json(r.stdout)
            if parsed is not None:
                print(json.dumps(parsed), flush=True)
                return
            errors.append(
                f"attempt {attempt}: rc=0 but no JSON: {r.stdout[-200:]!r}")
        else:
            tail = (r.stderr or "").strip().splitlines()
            errors.append(
                f"attempt {attempt}: rc={r.returncode}: " + " | ".join(tail[-3:]))
        _emit(errors, "provisional: attempt failed")
        time.sleep(min(10, max(0, deadline - time.monotonic() - SAFETY_MARGIN_S)))


def _save_baseline() -> None:
    ms, root, impl = measure_baseline()
    with open(BASELINE_FILE, "w") as f:
        json.dump(
            {
                "metric": "extend_commit_128_ms",
                "cpu_ms": ms,
                "data_root": root,
                "impl": impl,
            },
            f,
            indent=2,
        )
        f.write("\n")
    print(f"baseline measured: {ms:.1f} ms ({impl}) -> {BASELINE_FILE}",
          file=sys.stderr)


def _stream_batched() -> None:
    from celestia_app_tpu.parallel import streaming

    print(json.dumps(streaming.bench_stream_batched()))


def main() -> None:
    if "--child" in sys.argv:  # internal: parent-spawned measurement
        _run_child()
        return
    if "--list" in sys.argv:
        for name in sorted(MODES):
            _fn, metrics, desc = MODES[name]
            print(f"--{name:<18} {desc}")
            print(f"  {'':<18} emits: {metrics}")
        return
    for name, (fn, _metrics, _desc) in MODES.items():
        if f"--{name}" in sys.argv:
            fn()
            return
    _run_parent()


def measure_analyze(reps: int = 3) -> None:
    """Analysis-plane bench (--analyze): wall time of a full-tree run of
    every registered rule (tools/analyze, call-graph taint included)
    against the committed analyze.toml — the cost every tier-1 test run
    and pre-commit hook pays. Cold clears the per-file incremental
    cache first; warm re-runs against it (ISSUE 12 gate: warm ≤ cold/3
    — every file unchanged, so only the interprocedural re-link runs).
    Budget: < 10 s cold on CPU (pure-AST work). One BENCH JSON line:

      {"metric": "analyze_wall_s", ...,
       "analyze_cold_wall_s": F, "analyze_warm_wall_s": F,
       "analyze_effects_cold_wall_s": F, "analyze_effects_warm_wall_s": F}

    The effect pass (ISSUE 20: xfer-reach + lock-order +
    guarded-by-flow over the SCC summary fixpoint) is timed separately
    with its own cold/warm pair and the same warm ≤ cold/3 gate — the
    fragment cache must absorb the v4 effect facts too.
    """
    import os
    import tempfile

    from celestia_app_tpu.tools.analyze import run_analysis

    effect_rules = {"xfer-reach", "lock-order", "guarded-by-flow"}
    cache_path = os.path.join(tempfile.gettempdir(),
                              f"analyze_bench_cache_{os.getpid()}.json")
    best_cold = best_warm = None
    best_ecold = best_ewarm = None
    rep = None
    try:
        for _ in range(reps):
            if os.path.exists(cache_path):
                os.unlink(cache_path)
            cold = run_analysis(cache=cache_path)
            rep = warm = run_analysis(cache=cache_path)
            assert warm.cache_misses == 0, warm.cache_misses
            best_cold = (cold.wall_s if best_cold is None
                         else min(best_cold, cold.wall_s))
            best_warm = (warm.wall_s if best_warm is None
                         else min(best_warm, warm.wall_s))
        for _ in range(reps):
            if os.path.exists(cache_path):
                os.unlink(cache_path)
            ecold = run_analysis(cache=cache_path,
                                 only_rules=set(effect_rules))
            ewarm = run_analysis(cache=cache_path,
                                 only_rules=set(effect_rules))
            best_ecold = (ecold.wall_s if best_ecold is None
                          else min(best_ecold, ecold.wall_s))
            best_ewarm = (ewarm.wall_s if best_ewarm is None
                          else min(best_ewarm, ewarm.wall_s))
    finally:
        if os.path.exists(cache_path):
            os.unlink(cache_path)
    print(json.dumps({
        "metric": "analyze_wall_s",
        "analyze_wall_s": round(best_cold, 3),
        "analyze_cold_wall_s": round(best_cold, 3),
        "analyze_warm_wall_s": round(best_warm, 3),
        "warm_speedup": round(best_cold / max(best_warm, 1e-9), 1),
        "analyze_effects_cold_wall_s": round(best_ecold, 3),
        "analyze_effects_warm_wall_s": round(best_ewarm, 3),
        "files_scanned": rep.files_scanned,
        "rules_run": len(rep.rules_run),
        "violations": len(rep.violations),
        "errors": len(rep.errors),
        "waived": len(rep.waived),
        "budget_s": 10.0,
        "within_budget": best_cold < 10.0,
        "warm_within_third": best_warm <= best_cold / 3.0,
        "effects_warm_within_third": best_ewarm <= best_ecold / 3.0,
    }))


def measure_repair(reps: int | None = None) -> None:
    """Decode-plane bench (--repair). Two BENCH JSON lines:

      {"metric": "repair_128_ms", ...}  full 2D repair (da/repair.py
          batched sweep engine) of a ¼-erased k=128 EDS, measured for the
          two canonical masks — whole-columns-missing (the withholding
          shape: one shared erasure pattern, one fused decode matmul per
          sweep) and uniform-random cell loss (flaky-peer shape: distinct
          per-row patterns, scalar FWHT decode + batched device
          verification). Headline value is the whole-columns mask;
          acceptance is within 5x the same-backend extend+commit time
          measured in the SAME run.
      {"metric": "befp_verify_ms", ...}  da/fraud.verify_befp of a real
          k=128 bad-encoding proof (the DASer-fleet gossip-rate path).

    Backend labeling follows FORMATS §12.2: a CPU measurement is emitted
    as `"backend": "cpu-fallback"` so trajectory plots can tell labeled
    CPU stand-ins from TPU windows.
    """
    import jax

    from celestia_app_tpu.da import dah as dah_mod
    from celestia_app_tpu.da import eds as eds_mod
    from celestia_app_tpu.da import fraud, repair
    from celestia_app_tpu.ops import nmt
    from celestia_app_tpu.utils import telemetry

    if reps is None:
        # a CPU backend pays ~25 s/run at k=128; keep the whole mode
        # inside ~10 min there while accelerators get more samples
        reps = int(os.environ.get(
            "CELESTIA_BENCH_REPAIR_REPS",
            "2" if jax.devices()[0].platform == "cpu" else "5"))
    two_k = 2 * K
    ods = _bench_ods(K)
    # same-backend reference: the full extend+commit pipeline, warm-first
    # best-of-reps wall timing (the --admission scheme; each run ends in a
    # host fetch of the 32-byte data root, so the dispatch is complete —
    # the slope harness would cost 16 block executions, ~6 min on a CPU
    # backend, for the same answer)
    pipeline = eds_mod.jitted_pipeline(K)
    ods_dev = jax.device_put(ods)
    np.asarray(pipeline(ods_dev)[3])  # compile + warm
    extend_ms = None
    for _ in range(reps):
        t0 = time.perf_counter()
        np.asarray(pipeline(ods_dev)[3])
        dt = (time.perf_counter() - t0) * 1e3
        extend_ms = dt if extend_ms is None else min(extend_ms, dt)
    d, eds_obj, _ = dah_mod.new_dah_from_ods(ods)
    eds = np.asarray(eds_obj.squares)
    row_roots, col_roots = list(d.row_roots), list(d.col_roots)

    masks = {}
    m = np.ones((two_k, two_k), dtype=bool)
    m[:, ::4] = False  # every 4th extended column withheld: ¼ of cells
    masks["columns"] = m
    rng = np.random.default_rng(1)
    masks["random"] = rng.random((two_k, two_k)) >= 0.25

    timings, counter_split = {}, {}
    for name in ("columns", "random"):
        mask = masks[name]
        damaged = np.where(mask[..., None], eds, 0).astype(np.uint8)
        c0 = telemetry.snapshot().get("counters", {})
        out = repair.repair_eds(damaged, mask, row_roots, col_roots)
        assert np.array_equal(out, eds), f"repair({name}) diverged"
        c1 = telemetry.snapshot().get("counters", {})
        counter_split[name] = {
            key: c1.get(f"repair.{key}", 0) - c0.get(f"repair.{key}", 0)
            for key in ("axes_batched", "axes_scalar", "matrix_cache_hits",
                        "matrix_cache_misses")
        }
        best = None  # warm run above compiled every program; now measure
        for _ in range(reps):
            t0 = time.perf_counter()
            repair.repair_eds(damaged, mask, row_roots, col_roots)
            dt = (time.perf_counter() - t0) * 1e3
            best = dt if best is None else min(best, dt)
        timings[name] = best

    backend = jax.devices()[0].platform
    if backend == "cpu":
        backend = "cpu-fallback"
    print(json.dumps({
        "metric": "repair_128_ms",
        "value": round(timings["columns"], 2),
        "unit": "ms",
        "mask_columns_ms": round(timings["columns"], 2),
        "mask_random_ms": round(timings["random"], 2),
        "extend_commit_ms": round(extend_ms, 2),
        "vs_extend": round(timings["columns"] / extend_ms, 2),
        "within_5x_extend": timings["columns"] <= 5 * extend_ms,
        "counters": counter_split,
        "backend": backend,
    }), flush=True)

    # -- BEFP verification at gossip rate --------------------------------
    corrupt = eds.copy()
    corrupt[3, two_k - 1] ^= 0xFF  # row 3 is no longer a codeword
    t0 = time.perf_counter()
    bad_rows = nmt.eds_axis_roots(corrupt, np.arange(two_k), K)
    bad_cols = nmt.eds_axis_roots(
        np.ascontiguousarray(corrupt.transpose(1, 0, 2)),
        np.arange(two_k), K)
    d_bad = dah_mod.DataAvailabilityHeader(
        row_roots=tuple(r.tobytes() for r in bad_rows),
        col_roots=tuple(c.tobytes() for c in bad_cols),
    )
    commit_ms = (time.perf_counter() - t0) * 1e3
    t0 = time.perf_counter()
    befp = fraud.generate_befp(dah_mod.ExtendedDataSquare(corrupt), "row", 3)
    generate_ms = (time.perf_counter() - t0) * 1e3
    assert fraud.verify_befp(d_bad, befp), "BEFP did not verify"
    best = None
    for _ in range(max(reps, 3)):
        t0 = time.perf_counter()
        ok = fraud.verify_befp(d_bad, befp)
        dt = (time.perf_counter() - t0) * 1e3
        best = dt if best is None else min(best, dt)
    print(json.dumps({
        "metric": "befp_verify_ms",
        "value": round(best, 2),
        "unit": "ms",
        "k": K,
        "verified_fraud": bool(ok),
        "generate_ms": round(generate_ms, 2),
        "commit_corrupt_ms": round(commit_ms, 2),
        "backend": backend,
    }), flush=True)


def measure_admission(n_sigs: int = 512, n_senders: int = 32,
                      ingest_senders: int = 16,
                      ingest_txs_per_sender: int = 32) -> None:
    """Admission-plane bench (--admission). Two BENCH JSON lines:

      {"metric": "sig_verify_per_sec", ...}  batched secp256k1 ECDSA
          verification throughput (ops/secp256k1: vmapped 10x26-limb
          field math, complete RCB point formulas, GLV-halved doubling
          chain; one jit dispatch per 512 lanes) against the scalar
          `_py_verify` baseline measured IN THE SAME RUN — acceptance is
          >= 10x scalar on CPU; the >= 100k/s figure stays the recorded
          target for the next TPU relay window.
      {"metric": "mempool_ingest_txs_per_sec", ...}  CAT-pool ingest
          through the TWO-PHASE batched admission path
          (Node.broadcast_txs: one stateless batch-signature dispatch,
          then stateful per-tx CheckTx hitting the verified-sig cache) —
          directly comparable with the PR-2 scalar-path number from
          --mempool.
    """
    import random

    from celestia_app_tpu.chain import crypto
    from celestia_app_tpu.chain.app import App
    from celestia_app_tpu.chain.crypto import PrivateKey
    from celestia_app_tpu.chain.node import Node
    from celestia_app_tpu.chain.tx import MsgSend
    from celestia_app_tpu.client.tx_client import Signer
    from celestia_app_tpu.ops import secp256k1 as fast

    # -- 1) raw signature-verification throughput ------------------------
    privs = [PrivateKey.from_seed(b"adm-%d" % (i % n_senders))
             for i in range(n_sigs)]
    items = []
    for i, p in enumerate(privs):
        msg = b"admission-bench-%d" % i
        items.append((p.public_key().compressed, p.sign(msg), msg))

    scalar_n = min(48, n_sigs)
    t0 = time.perf_counter()
    for it in items[:scalar_n]:
        assert crypto._py_verify(*it)
    scalar_per_sec = scalar_n / (time.perf_counter() - t0)

    t0 = time.perf_counter()
    mask = fast.verify_batch(items)
    first_s = time.perf_counter() - t0  # includes the one-time jit compile
    assert mask.all()
    best = first_s
    for _ in range(3):
        t0 = time.perf_counter()
        fast.verify_batch(items)
        best = min(best, time.perf_counter() - t0)
    batched_per_sec = n_sigs / best
    backend = "scalar-fallback"
    if fast.available():
        import jax

        backend = jax.devices()[0].platform
    print(json.dumps({
        "metric": "sig_verify_per_sec",
        "value": round(batched_per_sec, 1),
        "unit": "sigs/s",
        "scalar_per_sec": round(scalar_per_sec, 1),
        "vs_scalar": round(batched_per_sec / scalar_per_sec, 2),
        "batch": n_sigs,
        "compile_s": round(first_s - best, 2),
        "backend": backend,
        "tpu_target_per_sec": 100_000,
    }), flush=True)

    # -- 2) two-phase mempool ingest -------------------------------------
    chain = "admission-bench"
    iprivs = [PrivateKey.from_seed(b"ing-%d" % i)
              for i in range(ingest_senders)]
    addrs = [p.public_key().address() for p in iprivs]
    app = App(chain_id=chain, engine="host")
    app.init_chain({
        "time_unix": 1_700_000_000.0,
        "accounts": [{"address": a.hex(), "balance": 10**12}
                     for a in addrs],
        "validators": [{"operator": addrs[0].hex(), "power": 10}],
    })
    signer = Signer(chain)
    for i, p in enumerate(iprivs):
        signer.add_account(p, number=i)
    rng = random.Random(0)
    raws: list[bytes] = []
    for _seq in range(ingest_txs_per_sender):
        for i, a in enumerate(addrs):
            tx = signer.create_tx(
                a, [MsgSend(a, addrs[(i + 1) % ingest_senders], 1)],
                fee=rng.randint(1_000, 100_000), gas_limit=100_000,
            )
            signer.accounts[a].sequence += 1
            raws.append(tx.encode())
    node = Node(app)
    t0 = time.perf_counter()
    results = node.broadcast_txs(raws)
    ingest_s = time.perf_counter() - t0
    admitted = sum(1 for r in results if r.code == 0)
    from celestia_app_tpu.utils import telemetry

    counters = telemetry.snapshot().get("counters", {})
    print(json.dumps({
        "metric": "mempool_ingest_txs_per_sec",
        "value": round(len(raws) / ingest_s, 1),
        "unit": "tx/s",
        "n_txs": len(raws),
        "admitted": admitted,
        "path": "two-phase-batched",
        "batch_verified": counters.get("admission.batch_verified", 0),
        "scalar_verified": counters.get("admission.sig_scalar_verified", 0),
    }), flush=True)

    # -- 3) traffic plane: the commitment half of phase 1 ----------------
    # a PFB burst through the same two-phase path, reported as the
    # commitment.* counter deltas (FORMATS §12.3; the throughput
    # head-to-head lives in --txsim — this line is the admission block's
    # counter surface)
    from celestia_app_tpu.da.blob import Blob
    from celestia_app_tpu.da.namespace import Namespace

    rng_np = np.random.default_rng(1)
    blob_raws = []
    # same lane count as the ingest burst (senders x txs_per_sender =
    # 512), so the phase-1 SIG batch reuses part 1/2's compiled bucket
    # and the measured rate prices admission, not a fresh jit compile
    for seq in range(ingest_txs_per_sender):
        for i, a in enumerate(addrs):
            blobs = [Blob(Namespace.v0(bytes([i + 1, (seq % 250) + 1]) * 5),
                          rng_np.integers(0, 256, 700, dtype=np.uint8)
                          .tobytes())]
            blob_raws.append(signer.create_pay_for_blobs(
                a, blobs, fee=300_000, gas_limit=5_000_000))
            signer.accounts[a].sequence += 1
    c0 = telemetry.snapshot().get("counters", {})
    t0 = time.perf_counter()
    blob_res = node.broadcast_txs(blob_raws)
    burst_s = time.perf_counter() - t0
    c1 = telemetry.snapshot().get("counters", {})

    def delta(name: str) -> int:
        return c1.get(name, 0) - c0.get(name, 0)

    print(json.dumps({
        "metric": "admission_commitment_batch",
        "value": round(len(blob_raws) / burst_s, 1),
        "unit": "blob-txs/s",
        "n_blob_txs": len(blob_raws),
        "admitted": sum(1 for r in blob_res if r.code == 0),
        "commitment_batch_dispatches": delta("commitment.batch_dispatches"),
        "commitment_batch_lanes": delta("commitment.batch_lanes"),
        "commitment_cache_hits": delta("commitment.cache_hits"),
        "commitment_recomputes": delta("commitment.recomputes"),
    }), flush=True)


def measure_mempool(n_senders: int = 16, txs_per_sender: int = 32) -> None:
    """Mempool plane microbench: CAT pool ingest (CheckTx + admission) and
    priority reap, pure host path (no device work). Signing happens before
    the clock starts — the measured path is what a node pays per inbound
    /broadcast_tx and per proposal. Prints two JSON lines:

      {"metric": "mempool_ingest_txs_per_sec", ...}
      {"metric": "mempool_reap_ms", ...}
    """
    import random

    from celestia_app_tpu.chain.app import App
    from celestia_app_tpu.chain.crypto import PrivateKey
    from celestia_app_tpu.chain.node import Node
    from celestia_app_tpu.chain.tx import MsgSend
    from celestia_app_tpu.client.tx_client import Signer

    chain = "mempool-bench"
    privs = [PrivateKey.from_seed(b"mp-%d" % i) for i in range(n_senders)]
    addrs = [p.public_key().address() for p in privs]
    app = App(chain_id=chain, engine="host")
    app.init_chain({
        "time_unix": 1_700_000_000.0,
        "accounts": [
            {"address": a.hex(), "balance": 10**12} for a in addrs
        ],
        "validators": [
            {"operator": addrs[0].hex(), "power": 10}
        ],
    })
    signer = Signer(chain)
    for i, p in enumerate(privs):
        signer.add_account(p, number=i)
    rng = random.Random(0)
    raws: list[bytes] = []
    for _seq in range(txs_per_sender):
        for i, a in enumerate(addrs):
            tx = signer.create_tx(
                a, [MsgSend(a, addrs[(i + 1) % n_senders], 1)],
                fee=rng.randint(1_000, 100_000), gas_limit=100_000,
            )
            signer.accounts[a].sequence += 1
            raws.append(tx.encode())

    node = Node(app)
    t0 = time.perf_counter()
    admitted = sum(1 for raw in raws if node.broadcast_tx(raw).code == 0)
    ingest_s = time.perf_counter() - t0
    reap_ms = []
    for _ in range(5):
        t0 = time.perf_counter()
        reaped = node._reap()
        reap_ms.append((time.perf_counter() - t0) * 1e3)
    print(json.dumps({
        "metric": "mempool_ingest_txs_per_sec",
        "value": round(len(raws) / ingest_s, 1),
        "unit": "tx/s",
        "n_txs": len(raws),
        "admitted": admitted,
    }))
    print(json.dumps({
        "metric": "mempool_reap_ms",
        "value": round(min(reap_ms), 3),
        "unit": "ms",
        "pool_count": len(reaped),
    }))


def measure_slo(heights: int = 3) -> None:
    """Fleet SLO verdict bench (--slo): spin a live 2-validator HTTP
    devnet, let it commit, quiesce the reactors, then run the fleet-wide
    SLO engine (tools/fleetmon.py) against it — and prove the verdict is
    DETERMINISTIC: two scrapes of the same quiesced fleet state must
    produce byte-identical verdicts. One BENCH JSON line:

      {"metric": "slo_verdict_pass", "value": 1|0, "deterministic": ...}
    """
    import threading  # noqa: F401  (ValidatorService spawns threads)

    from celestia_app_tpu.chain.crypto import PrivateKey
    from celestia_app_tpu.chain.reactor import ReactorConfig
    from celestia_app_tpu.chain import consensus as cons
    from celestia_app_tpu.service.validator_server import ValidatorService
    from celestia_app_tpu.tools import fleetmon

    privs = [PrivateKey.from_seed(b"slo-%d" % i) for i in range(2)]
    genesis = {
        "time_unix": 1_700_000_000.0,
        "accounts": [
            {"address": p.public_key().address().hex(), "balance": 10**12}
            for p in privs
        ],
        "validators": [
            {"operator": p.public_key().address().hex(), "power": 10,
             "pubkey": p.public_key().compressed.hex()}
            for p in privs
        ],
    }
    nodes = [cons.ValidatorNode(f"val{i}", p, genesis, "slo-bench")
             for i, p in enumerate(privs)]
    services = [ValidatorService(v) for v in nodes]
    for s in services:
        s.serve_background()
    urls = [f"http://127.0.0.1:{s.port}" for s in services]
    cfg = dict(timeout_propose=5.0, timeout_prevote=2.5,
               timeout_precommit=2.5, timeout_delta=0.5,
               block_interval=0.05, poll=0.01, gossip_timeout=1.5,
               sync_grace=0.5, breaker_reset=1.5)
    try:
        for i, s in enumerate(services):
            s.attach_reactor([u for j, u in enumerate(urls) if j != i],
                             ReactorConfig(**cfg))
        deadline = time.monotonic() + 120
        while (time.monotonic() < deadline
               and min(n.app.height for n in nodes) < heights):
            time.sleep(0.05)
        # quiesce: stop consensus, keep the HTTP planes serving — the
        # fleet state under judgment must hold still between scrapes
        for s in services:
            if s.reactor is not None:
                s.reactor.stop()
        rules = fleetmon.normalize_rules([
            {"name": "fleet-height", "source": "status", "path": "height",
             "op": ">=", "value": heights, "agg": "each"},
            {"name": "no-http-500", "metric": "http.500",
             "op": "==", "value": 0},
            {"name": "no-breaker-flaps", "metric": "net.breaker_open",
             "op": "==", "value": 0},
            {"name": "no-collector-errors",
             "metric": "telemetry.collector_errors",
             "op": "==", "value": 0},
            {"name": "commit-p99-budget", "metric": "commit",
             "kind": "p99", "op": "<=", "value": 60.0},
        ])
        v1 = fleetmon.evaluate(rules, fleetmon.scrape_fleet(
            urls, with_availability=False))
        v2 = fleetmon.evaluate(rules, fleetmon.scrape_fleet(
            urls, with_availability=False))
        deterministic = (fleetmon.verdict_bytes(v1)
                         == fleetmon.verdict_bytes(v2))
        print(json.dumps({
            "metric": "slo_verdict_pass",
            "value": 1 if v1["pass"] else 0,
            "unit": "bool",
            "deterministic": deterministic,
            "rules": len(rules),
            "failed": v1["failed"],
            "fleet_height": min(n.app.height for n in nodes),
        }), flush=True)
    finally:
        for s in services:
            try:
                s.shutdown()
            except Exception:
                pass


def run_compare() -> None:
    """Bench trajectory gate (--compare): align the repo's committed
    BENCH_*.json rounds (tools/benchdiff.py), print the per-metric
    trajectory, and exit 2 when the newest comparable sample of any
    metric regressed beyond tolerance — the CI gate over the committed
    perf history. cpu-fallback rounds never compare against hardware."""
    from celestia_app_tpu.tools import benchdiff

    here = os.path.dirname(os.path.abspath(__file__))
    raise SystemExit(benchdiff.main(["--dir", here]))


def measure_obs(blocks: int = 40, senders: int = 8) -> None:
    """Observability-plane overhead bench (--obs): the produce-block hot
    path with the FULL boundary observatory armed — spans + histograms +
    the transfer-ledger rows (obs/xfer.py, they follow the spans gate),
    per-site lock wait/hold profiling (racecheck, CELESTIA_LOCKPROF
    semantics flipped in-process), and a running GIL-pressure sampler
    (obs/gil.py) — vs the same path with everything off. One BENCH JSON
    line:

      {"metric": "obs_overhead_pct", ...}

    Each measured block carries real ante-checked MsgSend txs so the
    denominator is a representative block, not an empty square."""
    from celestia_app_tpu import obs as obs_mod
    from celestia_app_tpu.obs import gil
    from celestia_app_tpu.tools.analyze import racecheck
    from celestia_app_tpu.chain.app import App
    from celestia_app_tpu.chain.crypto import PrivateKey
    from celestia_app_tpu.chain.node import Node
    from celestia_app_tpu.chain.tx import MsgSend
    from celestia_app_tpu.client.tx_client import Signer

    privs = [PrivateKey.from_seed(b"obs-%d" % i) for i in range(senders)]
    addrs = [p.public_key().address() for p in privs]

    def run(n_blocks: int) -> list:
        """Fresh node; per-block ms for n_blocks tx-bearing blocks."""
        app = App(chain_id="obs-bench", engine="host")
        app.init_chain({
            "time_unix": 1_700_000_000.0,
            "accounts": [
                {"address": a.hex(), "balance": 10**12} for a in addrs
            ],
            "validators": [{"operator": addrs[0].hex(), "power": 10}],
        })
        node = Node(app)
        signer = Signer("obs-bench")
        for i, p in enumerate(privs):
            signer.add_account(p, number=i)

        def submit_round():
            for i, a in enumerate(addrs):
                tx = signer.create_tx(
                    a, [MsgSend(a, addrs[(i + 1) % senders], 1)],
                    fee=2000, gas_limit=100_000,
                )
                signer.accounts[a].sequence += 1
                node.broadcast_tx(tx.encode())

        t_block = 1_700_000_001.0
        submit_round()
        node.produce_block(t=t_block)  # warm caches outside the clock
        per_block = []
        for _ in range(n_blocks):
            t_block += 1.0
            t0 = time.perf_counter()
            submit_round()
            node.produce_block(t=t_block)
            per_block.append((time.perf_counter() - t0) * 1e3)
        return per_block

    # INTERLEAVED off/on arms, compared at the per-block p10 floor: on
    # a shared box the run-to-run load swing dwarfs a single-digit
    # overhead (observed >60% spread across identical runs, and a load
    # spike in any single block poisons a per-run mean). Interleaving
    # gives both arms the same shot at the quiet windows; the low
    # percentile of each arm's per-block times keeps only those, which
    # is the number the <5% gate is actually about — what the
    # observatory adds to a block, not what the neighbors add to the
    # box. The ON side arms the whole observatory per pair: span rows +
    # xfer ledger rows (spans gate), lock wait/hold profiling (locks
    # created by the instrumented Apps are born AFTER install, so they
    # are tracked), and the GIL oversleep sampler.
    def run_off(n: int) -> list:
        obs_mod.set_enabled(False)
        return run(n)

    def run_on(n: int) -> list:
        obs_mod.set_enabled(True)
        racecheck.install()
        racecheck.set_order_tracking(False)
        racecheck.set_profiling(True)
        gil.start("bench")
        try:
            return run(n)
        finally:
            gil.stop_all()
            racecheck.set_profiling(False)
            racecheck.uninstall()

    off_blocks, on_blocks = [], []
    try:
        run_off(4)  # discard: allocator/caches warm on nobody's clock
        for pair in range(4):
            # alternate which arm goes first so neither systematically
            # inherits the colder (or busier) half of its pair
            if pair % 2 == 0:
                off_blocks += run_off(blocks)
                on_blocks += run_on(blocks)
            else:
                on_blocks += run_on(blocks)
                off_blocks += run_off(blocks)
    finally:
        obs_mod.set_enabled(None)  # back to the CELESTIA_OBS env gate

    def floor(xs: list) -> float:
        return sorted(xs)[len(xs) // 10]  # p10: the quiet-window block

    off_ms, on_ms = floor(off_blocks), floor(on_blocks)
    overhead_pct = (on_ms - off_ms) / off_ms * 100.0
    print(json.dumps({
        "metric": "obs_overhead_pct",
        "value": round(overhead_pct, 2),
        "unit": "%",
        "instrumented_ms_per_block": round(on_ms, 3),
        "off_ms_per_block": round(off_ms, 3),
        "blocks": blocks,
        "txs_per_block": senders,
    }))


def measure_chaos(heights: int = 12, lost: int = 8) -> None:
    """Fault-plane recovery bench (--chaos). Two BENCH JSON lines:

      {"metric": "crash_replay_ms", ...}        WAL replay wall time for a
          node that lost its last `lost` durable commits but kept the WAL
          (the crash-matrix recovery path, chain/consensus.replay_wal)
      {"metric": "chaos_heal_recovery_s", ...}  wall time from healing a
          seeded full partition of a 3-reactor devnet to its next
          committed height (blocks-to-liveness after heal)
    """
    import shutil
    import tempfile
    import threading

    from celestia_app_tpu import faults
    from celestia_app_tpu.chain import consensus as cons
    from celestia_app_tpu.chain.crypto import PrivateKey
    from celestia_app_tpu.chain.reactor import ReactorConfig
    from celestia_app_tpu.chain.storage import ChainDB
    from celestia_app_tpu.service.validator_server import ValidatorService

    def genesis_for(privs, powers):
        return {
            "time_unix": 1_700_000_000.0,
            "accounts": [
                {"address": p.public_key().address().hex(),
                 "balance": 10**12} for p in privs
            ],
            "validators": [
                {"operator": p.public_key().address().hex(), "power": w,
                 "pubkey": p.public_key().compressed.hex()}
                for p, w in zip(privs, powers)
            ],
        }

    # -- 1) crash-replay wall time ---------------------------------------
    tmp = tempfile.mkdtemp(prefix="chaos-bench-")
    try:
        priv = PrivateKey.from_seed(b"chaos-replay")
        genesis = genesis_for([priv], [10])
        data_dir = os.path.join(tmp, "data")
        node = cons.ValidatorNode("val0", priv, genesis, "chaos-bench",
                                  data_dir=data_dir)
        net = cons.LocalNetwork([node])
        t = 1_700_000_000.0
        for _ in range(heights):
            t += 1.0
            net.produce_height(t=t)
        node.app.close()
        # the crash: the last `lost` durable commits vanish, the WAL stays
        keep = heights - lost
        db = ChainDB(data_dir)
        db.delete_above(keep)
        # the native engine's tomb_above removes the (sole) LATEST record
        # outright; re-point it at the surviving height (the file engine
        # already did this inside delete_above — set_latest is idempotent)
        db.backend.set_latest(keep)
        db.close()
        node2 = cons.ValidatorNode("val0", priv, genesis, "chaos-bench",
                                   data_dir=data_dir)
        node2.app.load()
        assert node2.app.height == keep
        t0 = time.perf_counter()
        replayed = node2.replay_wal()
        replay_ms = (time.perf_counter() - t0) * 1e3
        node2.app.close()
        assert replayed == lost, (replayed, lost)
        print(json.dumps({
            "metric": "crash_replay_ms",
            "value": round(replay_ms, 2),
            "unit": "ms",
            "blocks_replayed": replayed,
            "per_block_ms": round(replay_ms / max(replayed, 1), 2),
        }), flush=True)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    # -- 2) partition heal: blocks-to-liveness ---------------------------
    faults.reset(seed=7)
    privs = [PrivateKey.from_seed(b"chaos-%d" % i) for i in range(3)]
    genesis = genesis_for(privs, [10, 10, 10])
    nodes = [cons.ValidatorNode(f"val{i}", p, genesis, "chaos-bench")
             for i, p in enumerate(privs)]
    services = [ValidatorService(v) for v in nodes]
    for s in services:
        s.serve_background()
    urls = [f"http://127.0.0.1:{s.port}" for s in services]
    cfg = dict(timeout_propose=5.0, timeout_prevote=2.5,
               timeout_precommit=2.5, timeout_delta=0.5,
               block_interval=0.05, poll=0.01, gossip_timeout=1.5,
               sync_grace=0.5, breaker_reset=1.5)
    try:
        for i, s in enumerate(services):
            s.attach_reactor([u for j, u in enumerate(urls) if j != i],
                             ReactorConfig(**cfg))
        deadline = time.monotonic() + 120
        while (time.monotonic() < deadline
               and min(n.app.height for n in nodes) < 2):
            time.sleep(0.05)
        # isolate val0: no side holds >2/3 of 30 -> full stall
        ports = [s.port for s in services]
        faults.arm("net.request", "drop",
                   match={"owner": "^val0$"})
        faults.arm("net.request", "drop",
                   match={"owner": "^val[12]$", "peer": f":{ports[0]}$"})
        time.sleep(5.0)
        h0 = max(n.app.height for n in nodes)
        faults.disarm(point="net.request")
        t_heal = time.monotonic()
        deadline = time.monotonic() + 120
        while (time.monotonic() < deadline
               and max(n.app.height for n in nodes) <= h0):
            time.sleep(0.02)
        recovery_s = time.monotonic() - t_heal
        # liveness rate: heights committed in the 5 s after recovery
        h1 = max(n.app.height for n in nodes)
        time.sleep(5.0)
        rate = (max(n.app.height for n in nodes) - h1) / 5.0
        print(json.dumps({
            "metric": "chaos_heal_recovery_s",
            "value": round(recovery_s, 3),
            "unit": "s",
            "stalled_at": h0,
            "blocks_per_sec_after_heal": round(rate, 3),
        }), flush=True)
    finally:
        faults.reset()
        for s in services:
            try:
                s.shutdown()
            except Exception:
                pass


def measure_stream() -> None:
    """BASELINE config 4/5: streaming PrepareProposal — overlap host layout
    of block N+1 with device extend+commit of block N; prints blocks/s.
    See parallel/streaming.py."""
    from celestia_app_tpu.parallel import streaming

    print(json.dumps(streaming.bench_stream()))


def measure_stream_mesh() -> None:
    """BASELINE config 5: 256×256 streaming on an 8-device mesh — the
    sharded pipeline (two all-to-alls inside) streamed with host overlap;
    prints blocks/s. Virtual CPU devices demonstrate the same program when
    no multi-chip hardware is attached."""
    from celestia_app_tpu.parallel import streaming

    print(json.dumps(streaming.bench_stream_mesh()))


def measure_block(blocks: int | None = None, senders: int = 8) -> None:
    """Block-plane e2e bench (--block): the extend-once lifecycle end to
    end. Three BENCH JSON lines:

      {"metric": "block_e2e_ms", ...}       tx-bearing produce→commit wall
          time per block through Node.produce_block (prepare → process →
          finalize → commit — process hits the content-addressed EDS
          cache prepare populated, so the whole round dispatches exactly
          ONE extend; `extend_runs_per_block` reports the counter-
          verified figure).
      {"metric": "blocks_per_sec", ...}     inverse throughput over the
          same measured run.
      {"metric": "first_sample_after_commit_ms", ...}  first DAS sample
          after the final commit on the WARMED path (the commit handed
          its cache entry to the SampleCore with provers pre-built) vs
          the COLD rebuild path (caches cleared). The skip is counter-
          verified, not just faster wall time: the warm sample must show
          a `das.square_builds` delta of 0 and a `da.extend_runs` delta
          of 0, the cold one 1 and 1.

    Backend labeling follows FORMATS §12.2: a CPU measurement is emitted
    with `"backend": "cpu-fallback"`.
    """
    import shutil
    import tempfile

    import jax

    from celestia_app_tpu.chain.app import App
    from celestia_app_tpu.chain.crypto import PrivateKey
    from celestia_app_tpu.chain.node import Node
    from celestia_app_tpu.chain.tx import MsgSend
    from celestia_app_tpu.client.tx_client import Signer
    from celestia_app_tpu.das.server import SampleCore
    from celestia_app_tpu.utils import telemetry

    backend = jax.devices()[0].platform
    if blocks is None:
        blocks = int(os.environ.get(
            "CELESTIA_BENCH_BLOCKS", "10" if backend == "cpu" else "30"))
    if backend == "cpu":
        backend = "cpu-fallback"

    privs = [PrivateKey.from_seed(b"blk-%d" % i) for i in range(senders)]
    addrs = [p.public_key().address() for p in privs]
    tmp = tempfile.mkdtemp(prefix="block-bench-")
    app = App(chain_id="block-bench", engine="auto", data_dir=tmp)
    try:
        app.init_chain({
            "time_unix": 1_700_000_000.0,
            "accounts": [
                {"address": a.hex(), "balance": 10**12} for a in addrs
            ],
            "validators": [{"operator": addrs[0].hex(), "power": 10}],
        })
        node = Node(app)
        core = node.attach_das_core(SampleCore(app))
        signer = Signer("block-bench")
        for i, p in enumerate(privs):
            signer.add_account(p, number=i)

        def submit_round():
            for i, a in enumerate(addrs):
                tx = signer.create_tx(
                    a, [MsgSend(a, addrs[(i + 1) % senders], 1)],
                    fee=2000, gas_limit=100_000,
                )
                signer.accounts[a].sequence += 1
                node.broadcast_tx(tx.encode())

        def counters():
            return telemetry.snapshot().get("counters", {})

        def delta(c0, c1, key):
            return c1.get(key, 0) - c0.get(key, 0)

        t_block = 1_700_000_001.0
        submit_round()
        node.produce_block(t=t_block)  # compile + warm outside the clock
        app.da_warmer.wait_idle(60)

        c0 = counters()
        per_block = []
        t_run0 = time.perf_counter()
        for _ in range(blocks):
            t_block += 1.0
            submit_round()
            t0 = time.perf_counter()
            node.produce_block(t=t_block)
            per_block.append((time.perf_counter() - t0) * 1e3)
        run_s = time.perf_counter() - t_run0
        c1 = counters()
        extend_runs = delta(c0, c1, "da.extend_runs")
        print(json.dumps({
            "metric": "block_e2e_ms",
            "value": round(min(per_block), 3),
            "unit": "ms",
            "mean_ms": round(sum(per_block) / len(per_block), 3),
            "blocks": blocks,
            "txs_per_block": senders,
            "extend_runs_per_block": round(extend_runs / blocks, 3),
            "backend": backend,
        }), flush=True)
        print(json.dumps({
            "metric": "blocks_per_sec",
            "value": round(blocks / run_s, 3),
            "unit": "blocks/s",
            "blocks": blocks,
            "txs_per_block": senders,
            "backend": backend,
        }), flush=True)

        # -- first sample after commit: warmed vs cold -------------------
        app.da_warmer.wait_idle(60)
        height = app.height
        c_w0 = counters()
        t0 = time.perf_counter()
        core.sample(height, 0, 0)
        warm_ms = (time.perf_counter() - t0) * 1e3
        c_w1 = counters()
        warm_builds = delta(c_w0, c_w1, "das.square_builds")
        warm_extends = delta(c_w0, c_w1, "da.extend_runs")

        cold_core = SampleCore(app)  # no seed listener, fresh height LRU
        app.eds_cache.clear()  # the content cache must not rescue it
        c_c0 = counters()
        t0 = time.perf_counter()
        cold_core.sample(height, 0, 0)
        cold_ms = (time.perf_counter() - t0) * 1e3
        c_c1 = counters()
        print(json.dumps({
            "metric": "first_sample_after_commit_ms",
            "value": round(warm_ms, 3),
            "unit": "ms",
            "cold_ms": round(cold_ms, 3),
            "vs_cold": round(cold_ms / max(warm_ms, 1e-6), 1),
            "warm_square_builds": warm_builds,
            "warm_extend_runs": warm_extends,
            "cold_square_builds": delta(c_c0, c_c1, "das.square_builds"),
            "cold_extend_runs": delta(c_c0, c_c1, "da.extend_runs"),
            "skipped_square_build": warm_builds == 0 and warm_extends == 0,
            "backend": backend,
        }), flush=True)
    finally:
        app.close()
        shutil.rmtree(tmp, ignore_errors=True)


def measure_sync() -> None:
    """Sync-plane bench (--sync). Three BENCH JSON lines:

      {"metric": "snapshot_serve_ms", ...}   HTTP round-trip to serve the
          manifest list plus one chunk from the disk-backed snapshot
          store (never a capture, never under the service lock).
      {"metric": "blocksync_blocks_per_sec", ...}  verified replay rate
          of the pipelined range path (GET /gossip/commits + prefetch
          window) vs the per-height round-trip baseline, each measured
          over a real replay window of the SAME chain. A 70 ms
          per-request latency is injected via the fault plane — the
          network shape the reference's e2e benchmark models with
          BitTwister (test/e2e/benchmark/benchmark.go:110-117) — and
          labeled in the JSON; on bare localhost the replay loop is
          verification-bound either way and the round-trip being
          pipelined away would be invisible.
      {"metric": "state_sync_join_s", ...}   wall time for a fresh joiner
          to reach the tip of a `CELESTIA_BENCH_SYNC_BLOCKS` (default
          2000) block chain via chunked snapshot join + tail blocksync,
          against `full_replay_s` extrapolated from the measured
          per-height rate over the full chain length (flagged
          "estimated_from_window"; replaying thousands of blocks for
          real would measure the same per-height cost N more times).

    Backend labeling follows FORMATS §12.2 ("cpu-fallback" on CPU).
    """
    import shutil
    import tempfile
    import threading

    import jax

    from celestia_app_tpu import faults
    from celestia_app_tpu.chain import consensus as cons
    from celestia_app_tpu.chain import sync as sync_mod
    from celestia_app_tpu.chain.crypto import PrivateKey
    from celestia_app_tpu.chain.reactor import (
        ConsensusReactor,
        ReactorConfig,
    )
    from celestia_app_tpu.net import transport
    from celestia_app_tpu.service.validator_server import ValidatorService

    platform = jax.devices()[0].platform
    backend = "cpu-fallback" if platform == "cpu" else platform
    chain_id = "sync-bench"
    blocks = int(os.environ.get("CELESTIA_BENCH_SYNC_BLOCKS", "2000"))
    tail = 32  # heights past the newest snapshot (the join's replay tail)
    window = min(blocks, int(os.environ.get(
        "CELESTIA_BENCH_SYNC_WINDOW", "192")))
    base_window = min(blocks, int(os.environ.get(
        "CELESTIA_BENCH_SYNC_BASE_WINDOW", "96")))
    rtt_s = float(os.environ.get("CELESTIA_BENCH_SYNC_RTT_MS", "70")) / 1e3
    snap_interval = max(1, blocks // 4)

    def genesis_for(priv):
        return {
            "time_unix": 1_700_000_000.0,
            "accounts": [{"address": priv.public_key().address().hex(),
                          "balance": 10**12}],
            "validators": [{
                "operator": priv.public_key().address().hex(),
                "power": 10,
                "pubkey": priv.public_key().compressed.hex(),
            }],
        }

    def grow(vnode, reactor, n):
        for _ in range(n):
            height = vnode.app.height + 1
            last_cert = vnode.certificates.get(height - 1)
            block = vnode.propose(t=1_700_000_000.0 + height)
            bh = block.header.hash()
            digest = cons.Proposal.commit_info_digest(last_cert, ())
            sig = vnode.priv.sign(cons.Proposal.sign_bytes(
                chain_id, height, 0, bh, digest))
            prop = cons.Proposal(height, 0, block, vnode.address, sig,
                                 last_cert, ())
            vote = vnode._signed(height, bh, "precommit", 0)
            cert = cons.CommitCertificate(height, bh, (vote,), 0)
            vnode.apply(block, cert, absent_cert=last_cert)
            vnode.clear_lock()
            reactor._remember_commit(
                {"proposal": cons.proposal_to_json(prop),
                 "cert": cons.cert_to_json(cert)}, height)

    tmp = tempfile.mkdtemp(prefix="sync-bench-")
    faults.reset()
    try:
        priv = PrivateKey.from_seed(b"sync-bench-server")
        genesis = genesis_for(priv)
        server = cons.ValidatorNode(
            "srv", priv, genesis, chain_id,
            data_dir=os.path.join(tmp, "srv", "data"))
        svc = ValidatorService(server)
        reactor = ConsensusReactor(
            server, [], svc.lock,
            ReactorConfig(snapshot_interval=snap_interval,
                          snapshot_keep=2))
        svc.reactor = reactor  # serve routes only; loop not started
        svc.serve_background()
        url = f"http://127.0.0.1:{svc.port}"
        t_build0 = time.perf_counter()
        grow(server, reactor, blocks + tail)
        build_s = time.perf_counter() - t_build0
        target = server.app.height
        print(f"chain built: {target} heights in {build_s:.1f}s "
              f"(snapshots at interval {snap_interval})",
              file=sys.stderr, flush=True)

        # -- 1) snapshot_serve_ms (no injected latency: serve cost only)
        client = transport.PeerClient(name="sync-bench")
        reps = 20
        t0 = time.perf_counter()
        for _ in range(reps):
            snaps = client.get(url, "/sync/snapshots")["snapshots"]
            client.get(
                url,
                f"/sync/chunk?height={snaps[0]['height']}&index=0",
                raw=True,
            )
        serve_ms = (time.perf_counter() - t0) * 1e3 / reps
        print(json.dumps({
            "metric": "snapshot_serve_ms",
            "value": round(serve_ms, 3),
            "unit": "ms",
            "snapshots_on_disk": len(snaps),
            "n_chunks": snaps[0]["n_chunks"],
            "backend": backend,
        }), flush=True)

        # injected per-request latency (the reference's BitTwister shape):
        # applies to the JOINERS' outbound requests only — the serving
        # side makes none
        faults.arm("net.request", "delay", delay_s=rtt_s,
                   match={"owner": "^join-"})

        def joiner(name, **cfg):
            vnode = cons.ValidatorNode(
                name, PrivateKey.from_seed(name.encode()), genesis,
                chain_id, data_dir=os.path.join(tmp, name, "data"))
            defaults = dict(snapshot_interval=0, sync_grace=0.0,
                            gossip_timeout=10.0)
            r = ConsensusReactor(
                vnode, [url], threading.Lock(),
                ReactorConfig(**{**defaults, **cfg}))
            return vnode, r

        def replay_to(vnode, r, stop_height, budget_s=1800.0):
            with r._msg_lock:
                r._ahead = (stop_height + 1, url,
                            time.monotonic() - 10)
            deadline = time.monotonic() + budget_s
            while (vnode.app.height < stop_height
                   and time.monotonic() < deadline):
                r._maybe_catch_up()
            assert vnode.app.height >= stop_height, (
                f"{vnode.name} stuck at {vnode.app.height}")

        # -- 2) blocksync_blocks_per_sec: pipelined vs per-height -------
        vp, rp = joiner("join-pipe", statesync_gap=10**9)
        t0 = time.perf_counter()
        replay_to(vp, rp, window)
        pipe_s = time.perf_counter() - t0
        pipe_rate = window / pipe_s
        vb, rb = joiner("join-base", statesync_gap=10**9,
                        blocksync_pipeline=False)
        t0 = time.perf_counter()
        replay_to(vb, rb, base_window)
        base_s = time.perf_counter() - t0
        base_rate = base_window / base_s
        # differential check (untimed): walk both joiners to the SAME
        # height per-height (the two stop rules differ by one at window
        # boundaries), then the stores must be byte-identical — or the
        # speedup is measuring corruption
        while vb.app.height < vp.app.height:
            assert rb._replay_height(vb.app.height + 1, prefer=url)
        while vp.app.height < vb.app.height:
            assert rp._replay_height(vp.app.height + 1, prefer=url)
        assert vp.app.store.snapshot() == vb.app.store.snapshot(), (
            "pipelined and per-height replay diverged"
        )
        print(json.dumps({
            "metric": "blocksync_blocks_per_sec",
            "value": round(pipe_rate, 2),
            "unit": "blocks/s",
            "window_heights": window,
            "per_height_blocks_per_sec": round(base_rate, 2),
            "per_height_window_heights": base_window,
            "vs_per_height": round(pipe_rate / base_rate, 2),
            "injected_rtt_ms": rtt_s * 1e3,
            "backend": backend,
        }), flush=True)

        # -- 3) state_sync_join_s vs (estimated) full replay -------------
        vj, rj = joiner("join-snap", statesync_gap=tail)
        t0 = time.perf_counter()
        replay_to(vj, rj, target)
        join_s = time.perf_counter() - t0
        assert vj.app.last_app_hash == server.app.last_app_hash
        assert vj.app.last_block_hash == server.app.last_block_hash
        # full replay extrapolated from the measured per-height rate over
        # the same chain (labeled): replaying all N for real would just
        # re-measure base_rate N/base_window more times
        full_replay_s = target / base_rate
        print(json.dumps({
            "metric": "state_sync_join_s",
            "value": round(join_s, 2),
            "unit": "s",
            "chain_heights": target,
            "snapshot_height": target - target % snap_interval,
            "full_replay_s": round(full_replay_s, 1),
            "estimated_from_window": base_window,
            "vs_full_replay": round(full_replay_s / join_s, 1),
            "injected_rtt_ms": rtt_s * 1e3,
            "chain_build_s": round(build_s, 1),
            "backend": backend,
        }), flush=True)
        svc.shutdown()
        server.app.close()
        for v in (vp, vb, vj):
            v.app.close()
    finally:
        faults.reset()
        shutil.rmtree(tmp, ignore_errors=True)


def measure_serve() -> None:
    """Serving-plane bench (--serve). Per scheme, one BENCH JSON line:

      {"metric": "samples_served_per_sec", "scheme": S,
       "value": <pack-served samples/s>, "live_samples_per_sec": ...,
       "vs_live": ..., "p99_sample_ms": <live p99 per request>,
       "pack_p99_ms": ..., "pack_hit_ratio": ...,
       "sampler_round_trips_per_height": ..., "samplers": N, ...}

    Three measurements against one in-process devnet per scheme:

    - **live baseline**: `tools/dasload.py` drives N concurrent
      persistent-connection samplers (default 1000,
      ``CELESTIA_BENCH_SERVE_SAMPLERS``), each batching 16 drawn cells
      per request through the live `POST /das/samples` assembly path.
    - **pack-served**: the same fleet fetching static proof-pack chunks
      (`GET /das/pack/chunk`, sha256-verified against the manifest) for
      warm heights — no lock, no assembly; a chunk delivers every proof
      doc it covers, which is the pack model's serving economics.
    - **catch-up round-trips**: a real DASer (das/daser.py) light node
      catches up over the warm window via the multi-height batched
      sampler (one /das/headers + one grouped /das/samples per window);
      ``sampler_round_trips_per_height`` is the counter-verified
      sampling-path request count divided by heights sampled — the
      header-following (/ibc/header certificate) fetches are the light
      client's own sequential-verification cost, not the sampling
      plane's.

    Backend labeling follows FORMATS §12.2 ("cpu-fallback" on CPU).
    Env knobs: CELESTIA_BENCH_SERVE_SAMPLERS (1000), _REQUESTS (3),
    _K (16: the seeded load squares' ODS width), _WINDOW (8),
    _SCHEMES ("rs2d-nmt,cmt-ldpc").
    """
    import resource
    import shutil
    import tempfile

    import jax

    from celestia_app_tpu.chain import consensus as cons
    from celestia_app_tpu.chain import light as light_mod
    from celestia_app_tpu.chain.crypto import PrivateKey
    from celestia_app_tpu.da import edscache as edscache_mod
    from celestia_app_tpu.das.checkpoint import CheckpointStore
    from celestia_app_tpu.das.daser import DASer, DASerConfig
    from celestia_app_tpu.service.server import NodeService
    from celestia_app_tpu.tools import dasload
    from celestia_app_tpu.utils import telemetry

    platform = jax.devices()[0].platform
    backend = "cpu-fallback" if platform == "cpu" else platform
    samplers = int(os.environ.get("CELESTIA_BENCH_SERVE_SAMPLERS", "1000"))
    requests = int(os.environ.get("CELESTIA_BENCH_SERVE_REQUESTS", "3"))
    k_load = int(os.environ.get("CELESTIA_BENCH_SERVE_K", "16"))
    window = int(os.environ.get("CELESTIA_BENCH_SERVE_WINDOW", "8"))
    schemes = os.environ.get("CELESTIA_BENCH_SERVE_SCHEMES",
                             "rs2d-nmt,cmt-ldpc").split(",")
    # a thousand keep-alive samplers hold a thousand sockets each side
    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    if soft < 4 * samplers:
        resource.setrlimit(resource.RLIMIT_NOFILE,
                           (min(4 * samplers, hard), hard))

    def genesis_for(priv):
        return {
            "time_unix": 1_700_000_000.0,
            "accounts": [{"address": priv.public_key().address().hex(),
                          "balance": 10**12}],
            "validators": [{
                "operator": priv.public_key().address().hex(),
                "power": 10,
                "pubkey": priv.public_key().compressed.hex(),
            }],
        }

    def grow(vnode, n):
        for _ in range(n):
            height = vnode.app.height + 1
            last_cert = vnode.certificates.get(height - 1)
            block = vnode.propose(t=1_700_000_000.0 + height)
            bh = block.header.hash()
            vote = vnode._signed(height, bh, "precommit", 0)
            cert = cons.CommitCertificate(height, bh, (vote,), 0)
            vnode.apply(block, cert, absent_cert=last_cert)
            vnode.clear_lock()

    def counters():
        return telemetry.snapshot().get("counters", {})

    for scheme in schemes:
        chain_id = f"serve-bench-{scheme}"
        tmp = tempfile.mkdtemp(prefix="serve-bench-")
        try:
            priv = PrivateKey.from_seed(b"serve-bench")
            genesis = genesis_for(priv)
            vnode = cons.ValidatorNode(
                "srv", priv, genesis, chain_id,
                data_dir=os.path.join(tmp, "srv", "data"),
                da_scheme=scheme, pack_keep=0)  # keep every pack
            svc = NodeService(vnode, port=0)
            svc.serve_background()
            url = f"http://127.0.0.1:{svc.port}"
            grow(vnode, window)
            vnode.app.da_warmer.wait_idle(60)
            # every chain height needs its pack for the warm window
            # (the warmer coalesces under rapid commits; build is
            # idempotent for the ones it did reach)
            for h in range(1, vnode.app.height + 1):
                vnode.app.pack_store.build(
                    h, svc.das_core._entry(h).cache_entry)

            # seeded load heights: k_load squares are the meatier
            # serving shape (the chain's own empty blocks are k=1)
            rng = np.random.default_rng(0)
            load_heights = []
            for i in range(4):
                ods = rng.integers(0, 256, size=(k_load, k_load, 512),
                                   dtype=np.uint8)
                ods[..., :29] = 0
                ods[..., 28] = 7
                entry = edscache_mod.compute_entry(ods, "host",
                                                   scheme=scheme)
                h = 1000 + i
                svc.das_core.seed_scheme_entry(h, entry)
                vnode.app.pack_store.build(h, entry)
                load_heights.append(h)

            live = dasload.run_load(url, load_heights,
                                    samplers=samplers, requests=requests,
                                    cells=16, mode="live")
            print(f"[{scheme}] live: {live['samples_per_sec']}/s "
                  f"p99 {live['p99_ms']}ms errors {live['errors']}",
                  file=sys.stderr, flush=True)
            pack = dasload.run_load(url, load_heights,
                                    samplers=samplers, requests=requests,
                                    cells=16, mode="pack")
            print(f"[{scheme}] pack: {pack['samples_per_sec']}/s "
                  f"p99 {pack['p99_ms']}ms errors {pack['errors']}",
                  file=sys.stderr, flush=True)

            # -- catch-up round trips: a real DASer over the warm window
            trust = light_mod.TrustedState(
                height=0, header_hash=b"",
                validators={vnode.address:
                            priv.public_key().compressed},
                powers={vnode.address: 10},
            )
            daser = DASer(
                [url], light_mod.LightClient(chain_id, trust),
                CheckpointStore(os.path.join(tmp, "cp.json")),
                cfg=DASerConfig(samples_per_header=16, workers=1,
                                job_size=window, retries=2,
                                backoff=0.01),
                rng=np.random.default_rng(7), name="serve-bench-daser",
            )
            c0 = counters()
            out = daser.sync()
            c1 = counters()
            trips = (c1.get("daser.sampling_round_trips", 0)
                     - c0.get("daser.sampling_round_trips", 0))
            heights_swept = (c1.get("daser.heights_swept", 0)
                             - c0.get("daser.heights_swept", 0))
            rtph = trips / max(1, heights_swept)
            sampled_ok = len(out.get("sampled", [])) == window
            vs_live = (pack["samples_per_sec"]
                       / max(1e-9, live["samples_per_sec"]))
            print(json.dumps({
                "metric": "samples_served_per_sec",
                "value": pack["samples_per_sec"],
                "unit": "samples/s",
                "scheme": scheme,
                "live_samples_per_sec": live["samples_per_sec"],
                "vs_live": round(vs_live, 2),
                "p99_sample_ms": live["p99_ms"],
                "pack_p99_ms": pack["p99_ms"],
                "pack_hit_ratio": pack["pack_hit_ratio"],
                "sampler_round_trips_per_height": round(rtph, 3),
                "window_heights": window,
                "window_sampled_ok": sampled_ok,
                "samplers": samplers,
                "requests_per_sampler": requests,
                "cells_per_request": 16,
                "load_square_k": k_load,
                "live_errors": live["errors"],
                "pack_errors": pack["errors"],
                "backend": backend,
            }), flush=True)
            svc.shutdown()
            vnode.app.close()
        finally:
            shutil.rmtree(tmp, ignore_errors=True)


def measure_read() -> None:
    """Read-plane bench (--read). One BENCH JSON line:

      {"metric": "namespace_queries_per_sec", "value": <batched qps>,
       "single_queries_per_sec": ..., "batched_vs_single_ratio": ...,
       "single_p50_ms"/"single_p99_ms"/"batch_p50_ms"/"batch_p99_ms",
       "pack_queries_per_sec", "pack_vs_live_ratio", "present_ratio",
       "readers", "batch", "backend"}

    Three measurements against one in-process devnet carrying real PFB
    blob blocks (many distinct namespaces per height):

    - **single baseline**: `tools/blobload.py` drives N concurrent
      persistent-connection followers, each resolving one namespace per
      `GET /blob/get` round-trip — the per-request host reference loop
      (da/namespace_data.get_namespace_data per query).
    - **batched**: the same query stream folded ``batch`` queries per
      `POST /blob/namespaces` round-trip — one engine-gated batched
      search (da/namespace_device.py) resolves each height's whole
      batch. ``batched_vs_single_ratio`` is the ISSUE 16 gate (>= 5x at
      batch >= 64).
    - **pack-served**: static blob-pack chunk reads (sha256-verified),
      the CDN path; ``pack_vs_live_ratio`` is pack qps over single qps.

    Backend labeling follows FORMATS §12.2 ("cpu-fallback" on CPU).
    Env knobs: CELESTIA_BENCH_READ_READERS (64), _REQUESTS (6),
    _BATCH (64), _BLOCKS (3), _NS (48 distinct namespaces).
    """
    import resource
    import shutil
    import tempfile

    import jax

    from celestia_app_tpu.chain import consensus as cons
    from celestia_app_tpu.chain.crypto import PrivateKey
    from celestia_app_tpu.client.tx_client import Signer
    from celestia_app_tpu.da.blob import Blob
    from celestia_app_tpu.da.namespace import Namespace
    from celestia_app_tpu.service.server import NodeService
    from celestia_app_tpu.tools import blobload

    platform = jax.devices()[0].platform
    backend = "cpu-fallback" if platform == "cpu" else platform
    readers = int(os.environ.get("CELESTIA_BENCH_READ_READERS", "64"))
    requests = int(os.environ.get("CELESTIA_BENCH_READ_REQUESTS", "6"))
    batch = int(os.environ.get("CELESTIA_BENCH_READ_BATCH", "64"))
    blocks = int(os.environ.get("CELESTIA_BENCH_READ_BLOCKS", "3"))
    n_ns = int(os.environ.get("CELESTIA_BENCH_READ_NS", "48"))
    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    if soft < 4 * readers:
        resource.setrlimit(resource.RLIMIT_NOFILE,
                           (min(4 * readers, hard), hard))

    chain_id = "read-bench"
    tmp = tempfile.mkdtemp(prefix="read-bench-")
    try:
        n_accounts = 8
        privs = [PrivateKey.from_seed(b"read-bench-%d" % i)
                 for i in range(n_accounts)]
        addrs = [p.public_key().address() for p in privs]
        genesis = {
            "time_unix": 1_700_000_000.0,
            "accounts": [{"address": a.hex(), "balance": 10**14}
                         for a in addrs],
            "validators": [{
                "operator": addrs[0].hex(),
                "power": 10,
                "pubkey": privs[0].public_key().compressed.hex(),
            }],
        }
        vnode = cons.ValidatorNode(
            "read", privs[0], genesis, chain_id,
            data_dir=os.path.join(tmp, "read", "data"),
            da_scheme="rs2d-nmt", pack_keep=0)
        signer = Signer(chain_id)
        for i, p in enumerate(privs):
            signer.add_account(p, number=i)
        svc = NodeService(vnode, port=0)
        svc.serve_background()
        url = f"http://127.0.0.1:{svc.port}"

        namespaces = [Namespace.v0(bytes([1 + i // 200, 1 + i % 200]) * 5)
                      for i in range(n_ns)]
        rng = np.random.default_rng(16)

        def pfb_blobs(height):
            # every namespace present at every height, blobs spread
            # over the accounts so each block carries n_accounts PFBs
            per_acct = [[] for _ in range(n_accounts)]
            for i, ns in enumerate(namespaces):
                size = int(rng.integers(400, 1200))
                per_acct[i % n_accounts].append(
                    Blob(ns, rng.integers(0, 256, size,
                                          dtype=np.uint8).tobytes()))
            return per_acct

        for _ in range(blocks):
            height = vnode.app.height + 1
            for a, blobs in zip(addrs, pfb_blobs(height)):
                raw = signer.create_pay_for_blobs(
                    a, blobs, fee=300_000, gas_limit=50_000_000)
                signer.accounts[a].sequence += 1
                vnode.add_tx(raw)
            last_cert = vnode.certificates.get(height - 1)
            block = vnode.propose(t=1_700_000_000.0 + height)
            bh = block.header.hash()
            vote = vnode._signed(height, bh, "precommit", 0)
            cert = cons.CommitCertificate(height, bh, (vote,), 0)
            vnode.apply(block, cert, absent_cert=last_cert)
            vnode.clear_lock()
        vnode.app.da_warmer.wait_idle(60)
        # the warmer coalesces under rapid commits; builds are
        # idempotent for the heights it did reach
        heights = list(range(1, vnode.app.height + 1))
        for h in heights:
            vnode.app.blob_pack_store.build(
                h, svc.das_core._entry(h).cache_entry)
        ns_hex = [ns.raw.hex() for ns in namespaces]

        single = blobload.run_load(url, heights, ns_hex,
                                   readers=readers, requests=requests,
                                   mode="single")
        print(f"single: {single['namespace_queries_per_sec']}/s "
              f"p99 {single['p99_ms']}ms errors {single['errors']}",
              file=sys.stderr, flush=True)
        batched = blobload.run_load(url, heights, ns_hex,
                                    readers=max(2, readers // 8),
                                    requests=requests, mode="batch",
                                    batch=batch)
        print(f"batch({batch}): "
              f"{batched['namespace_queries_per_sec']}/s "
              f"p99 {batched['p99_ms']}ms errors {batched['errors']}",
              file=sys.stderr, flush=True)
        pack = blobload.run_load(url, heights, ns_hex,
                                 readers=readers, requests=requests,
                                 mode="pack")
        print(f"pack: {pack['namespace_queries_per_sec']}/s "
              f"p99 {pack['p99_ms']}ms errors {pack['errors']}",
              file=sys.stderr, flush=True)

        single_qps = single["namespace_queries_per_sec"]
        batch_qps = batched["namespace_queries_per_sec"]
        pack_qps = pack["namespace_queries_per_sec"]
        print(json.dumps({
            "metric": "namespace_queries_per_sec",
            "value": batch_qps,
            "unit": "queries/s",
            "single_queries_per_sec": single_qps,
            "batched_vs_single_ratio": round(
                batch_qps / max(1e-9, single_qps), 2),
            "single_p50_ms": single["p50_ms"],
            "single_p99_ms": single["p99_ms"],
            "batch_p50_ms": batched["p50_ms"],
            "batch_p99_ms": batched["p99_ms"],
            "pack_queries_per_sec": pack_qps,
            "pack_vs_live_ratio": round(
                pack_qps / max(1e-9, single_qps), 2),
            "present_ratio": batched["present_ratio"],
            "heights": len(heights),
            "namespaces": n_ns,
            "readers": readers,
            "batch": batch,
            "single_errors": single["errors"],
            "batch_errors": batched["errors"],
            "pack_errors": pack["errors"],
            "backend": backend,
        }), flush=True)
        svc.shutdown()
        vnode.app.close()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def measure_txsim() -> None:
    """Traffic-plane bench (--txsim). Three BENCH JSON lines:

      {"metric": "blobs_per_sec", ...}  sustained blob load: N concurrent
          txsim sequences (tools/txsim.run_load — one Signer account and
          one persistent keep-alive HttpNodeClient each) submit PFB
          blobs over HTTP against a live in-process devnet whose
          producer commits blocks on an interval; every tx is
          confirm-polled, so the number is END-TO-END admission->commit
          blob throughput. Carries admission_commit p50/p99 and the run's
          acceptance counts.
      {"metric": "admission_commit_p99_ms", ...}  the same run's p99
          submit->commit latency as its own metric line.
      {"metric": "commitment_validate_per_sec", ...}  the tentpole's
          head-to-head: admission commitment validation CACHED (one
          batched prevalidation dispatch filling the
          VerifiedCommitmentCache, then per-tx lookups) vs the COLD
          per-tx host path (per-blob subtree-root MMRs in host Python,
          the reference's ValidateBlobTx shape) over the same
          >= 64-pending-blob set — acceptance is >= 3x at >= 64 blobs.

    Backend labeling follows FORMATS §12.2 ("cpu-fallback" on CPU).
    Env knobs: CELESTIA_BENCH_TXSIM_SEQUENCES (8), _TXS (8: per
    sequence), _BLOBS (128: head-to-head pending set),
    _BLOCK_TIME (0.2 s).
    """
    import jax

    from celestia_app_tpu import appconsts
    from celestia_app_tpu.chain import admission
    from celestia_app_tpu.chain import blob_validation
    from celestia_app_tpu.chain.app import App
    from celestia_app_tpu.chain.crypto import PrivateKey
    from celestia_app_tpu.chain.node import Node
    from celestia_app_tpu.client.tx_client import Signer
    from celestia_app_tpu.da import blob as blob_mod
    from celestia_app_tpu.da.blob import Blob
    from celestia_app_tpu.da.namespace import Namespace
    from celestia_app_tpu.service.server import NodeService
    from celestia_app_tpu.tools import txsim
    from celestia_app_tpu.utils import telemetry

    platform = jax.devices()[0].platform
    backend = "cpu-fallback" if platform == "cpu" else platform
    n_seq = int(os.environ.get("CELESTIA_BENCH_TXSIM_SEQUENCES", "8"))
    txs_per_seq = int(os.environ.get("CELESTIA_BENCH_TXSIM_TXS", "8"))
    n_blobs = int(os.environ.get("CELESTIA_BENCH_TXSIM_BLOBS", "128"))
    block_time = float(os.environ.get("CELESTIA_BENCH_TXSIM_BLOCK_TIME",
                                      "0.2"))

    # -- 1) sustained load against a live devnet -------------------------
    import shutil
    import tempfile

    chain = "txsim-bench"
    privs = [PrivateKey.from_seed(b"txsim-bench-%d" % i)
             for i in range(n_seq)]
    addrs = [p.public_key().address() for p in privs]
    tmp = tempfile.mkdtemp(prefix="txsim-bench-")
    app = app_w = app_c = None
    try:
        # a data_dir so /abci_query path=tx (the confirm-polling route)
        # has a block store to resolve against — like any real devnet home
        app = App(chain_id=chain, engine="auto",
                  data_dir=os.path.join(tmp, "data"))
        app.init_chain({
            "time_unix": 1_700_000_000.0,
            "accounts": [{"address": a.hex(), "balance": 10**14}
                         for a in addrs],
            "validators": [{"operator": addrs[0].hex(), "power": 10}],
        })
        node = Node(app)
        svc = NodeService(node, port=0)
        svc.serve_background()
        url = f"http://127.0.0.1:{svc.port}"
        signer = Signer(chain)
        for i, p in enumerate(privs):
            signer.add_account(p, number=i)

        def produce():
            with svc.lock:
                node.produce_block()

        # warm the block pipeline's jit buckets before the measured window
        # (a live devnet is warm; the submit->commit latency must price the
        # traffic plane, not the first blocks' one-time compiles)
        rng_w = np.random.default_rng(9)
        for _r in range(3):
            for i, a in enumerate(addrs[:2]):
                wblobs = [Blob(Namespace.v0(bytes([99, i + 1]) * 5),
                               rng_w.integers(
                                   0, 256, int(rng_w.integers(100, 2000)),
                                   dtype=np.uint8).tobytes())]
                wraw = signer.create_pay_for_blobs(
                    a, wblobs, fee=300_000, gas_limit=5_000_000)
                if node.broadcast_tx(wraw).code == 0:
                    signer.accounts[a].sequence += 1
            produce()

        driver = txsim.BlockDriver(produce, block_time=block_time)
        driver.start()
        c0 = telemetry.snapshot().get("counters", {})
        try:
            rep = txsim.run_load(
                [url], signer, addrs,
                txsim.LoadConfig(blob_sequences=n_seq,
                                 txs_per_sequence=txs_per_seq,
                                 blob_sizes=(100, 2000), blobs_per_pfb=(1, 2),
                                 confirm_timeout_s=60.0, seed=0),
            )
        finally:
            driver.stop()
            svc.shutdown()
        c1 = telemetry.snapshot().get("counters", {})

        def delta(name: str) -> int:
            return c1.get(name, 0) - c0.get(name, 0)

        print(json.dumps({
            "metric": "blobs_per_sec",
            "value": rep.blobs_per_sec,
            "unit": "blobs/s",
            "sequences": rep.sequences,
            "txs_per_sequence": txs_per_seq,
            "pfbs_submitted": rep.pfbs_submitted,
            "pfbs_accepted": rep.pfbs_accepted,
            "pfbs_confirmed": rep.pfbs_confirmed,
            "blobs_confirmed": rep.blobs_confirmed,
            "bytes_submitted": rep.bytes_submitted,
            "admission_commit_p50_ms": rep.admission_commit_p50_ms,
            "admission_commit_p99_ms": rep.admission_commit_p99_ms,
            "blocks_produced": driver.blocks,
            "block_time_s": block_time,
            "resyncs": rep.resyncs,
            "errors": rep.errors,
            "commitment_cache_hits": delta("commitment.cache_hits"),
            "commitment_recomputes": delta("commitment.recomputes"),
            "backend": backend,
        }), flush=True)
        print(json.dumps({
            "metric": "admission_commit_p99_ms",
            "value": rep.admission_commit_p99_ms,
            "unit": "ms",
            "p50_ms": rep.admission_commit_p50_ms,
            "sequences": rep.sequences,
            "confirmed": rep.pfbs_confirmed + rep.sends_confirmed,
            "backend": backend,
        }), flush=True)

        # -- 2) cached vs cold commitment-validation throughput --------------
        # COLD is the reference's shape: every validation phase recomputes
        # each blob's commitment per tx in host Python (ValidateBlobTx in
        # both CheckTx and ProcessProposal). CACHED is this PR's shape: ONE
        # batched prevalidation dispatch at admission fills the
        # VerifiedCommitmentCache, and every validation phase after it
        # (CheckTx -> Prepare -> Process -> replay — 3+ passes per tx) is a
        # lookup + byte-compare. `value` is the per-pass cached validation
        # throughput (what each phase now pays); `admission_dispatch_s` and
        # `incl_dispatch_per_sec` price the one-time batch honestly.
        threshold = appconsts.subtree_root_threshold(1)
        # devnet-scale blobs (the reference txsim submits up to ~100 KB);
        # commitment cost scales with shares, so the size range is the knob
        # that decides how much each phase's recompute used to cost
        blob_lo_hi = [int(x) for x in os.environ.get(
            "CELESTIA_BENCH_TXSIM_BLOB_BYTES", "1000-16000").split("-")]

        def blob_tx_set(tag: bytes):
            # same seed per set: identical shapes (jit buckets stay warm
            # across sets), distinct namespaces keep the cache keys apart
            rng = np.random.default_rng(2)
            signer2 = Signer(chain)
            for i, p in enumerate(privs):
                signer2.add_account(p, number=i)
            raws = []
            for i in range(n_blobs):
                a = addrs[i % len(addrs)]
                size = int(rng.integers(blob_lo_hi[0], blob_lo_hi[1] + 1))
                blobs = [Blob(Namespace.v0(tag + bytes([i % 251, i // 251]) * 4),
                              rng.integers(0, 256, size, dtype=np.uint8)
                              .tobytes())]
                raws.append(signer2.create_pay_for_blobs(
                    a, blobs, fee=300_000, gas_limit=5_000_000))
                signer2.accounts[a].sequence += 1
            return [blob_mod.try_unmarshal_blob_tx(r) for r in raws], raws

        # warm the jit shape buckets so the dispatch number is steady-state
        # (the one-time compile is reported separately, like --admission)
        _warm_btxs, warm_raws = blob_tx_set(b"wa")
        app_w = App(chain_id=chain, engine="auto")
        t0 = time.perf_counter()
        admission.prevalidate_commitments(app_w, warm_raws)
        compile_s = time.perf_counter() - t0

        from celestia_app_tpu.da import commitment as commitment_mod

        cold_btxs, _ = blob_tx_set(b"co")
        cold_items = [(btx.blobs[0], btx) for btx in cold_btxs]
        # the commitment-validation component alone — the work the cache
        # eliminates from every phase (per-blob host subtree-root MMR +
        # byte-compare, the reference's ValidateBlobTx recompute):
        t0 = time.perf_counter()
        for blob, btx in cold_items:
            want = commitment_mod.create_commitment(blob, threshold)
            assert want is not None
        cold_s = time.perf_counter() - t0
        # and the whole validate_blob_tx (decode + gates + commitment), the
        # end-to-end per-phase cost:
        t0 = time.perf_counter()
        for btx in cold_btxs:
            blob_validation.validate_blob_tx(btx, threshold)  # per-tx host
        cold_full_s = time.perf_counter() - t0

        cached_btxs, cached_raws = blob_tx_set(b"ca")
        app_c = App(chain_id=chain, engine="auto")
        t0 = time.perf_counter()
        admission.prevalidate_commitments(app_c, cached_raws)
        dispatch_s = time.perf_counter() - t0
        cache = app_c.commitment_cache
        t0 = time.perf_counter()
        for btx in cached_btxs:
            blob = btx.blobs[0]
            got = cache.hit(cache.key(blob.namespace.raw, blob.share_version,
                                      blob.data, threshold))
            assert got is not None
        cached_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        for btx in cached_btxs:
            blob_validation.validate_blob_tx(btx, threshold, cache=cache)
        cached_full_s = time.perf_counter() - t0

        cold_per_sec = n_blobs / cold_s
        cached_per_sec = n_blobs / cached_s
        print(json.dumps({
            "metric": "commitment_validate_per_sec",
            "value": round(cached_per_sec, 1),
            "unit": "blobs/s",
            "cold_per_sec": round(cold_per_sec, 1),
            "vs_cold": round(cached_per_sec / cold_per_sec, 2),
            "full_validate_per_sec": round(n_blobs / cached_full_s, 1),
            "full_validate_cold_per_sec": round(n_blobs / cold_full_s, 1),
            "full_vs_cold": round(cold_full_s / cached_full_s, 2),
            "pending_blobs": n_blobs,
            "blob_bytes": blob_lo_hi,
            "admission_dispatch_s": round(dispatch_s, 4),
            "incl_dispatch_per_sec": round(
                n_blobs / (dispatch_s + cached_s), 1),
            "compile_s": round(max(0.0, compile_s - dispatch_s), 2),
            "path": "one-batched-dispatch+cache-lookups vs per-tx-host",
            "backend": backend,
        }), flush=True)
    finally:
        # a failed run must not strand the temp block store or a
        # flock-holding App (review hardening)
        for a in (app, app_w, app_c):
            if a is not None:
                try:
                    a.close()
                except Exception:
                    pass
        shutil.rmtree(tmp, ignore_errors=True)


# -- mode registry (--list prints it) ----------------------------------------
# name -> (runner, emitted metrics, one-line description). The default
# invocation (no flag) runs the deadline-driven headline measurement
# (`extend_commit_128_ms`).
def measure_mesh() -> None:
    """Mesh-plane bench (--mesh). Three BENCH JSON lines:

      {"metric": "extend_commit_256_ms", ...}  one ODS -> device-resident
          entry (extend + NMT commit) through the mesh engine
          (parallel/mesh_engine.compute_entry_mesh: sharded shard_map
          pipeline, commitments fetched to host, EDS left on-mesh).
          k=256 is the target size; on the CPU fallback a smaller square
          is measured (GF(2^16) matmuls at k=256 take minutes of host
          time) and the JSON says so via "k"/"target_k" — hardware
          numbers stay frozen at round 4 until the relay returns.
      {"metric": "blocks_per_sec_batched", ...}  the produce path's
          multi-block batched dispatch (B squares per launch,
          device-resident entries) vs the per-block production pipeline
          (one dispatch + one full-EDS host fetch per block — what
          edscache.compute_entry's single-device path pays today).
          Counter-verified: "host_crossings_per_block" is the measured
          edscache.host_crossings delta per batched block (0 on the
          warmed produce path — nothing materializes until a proof is
          actually served). On the CPU fallback both paths run the same
          FLOPs on the same cores, so the dispatch-boundary cost the
          batching removes (the relay round-trip BENCH_HW_r4 blames for
          3.1 vs ~90 blocks/s) is modeled the way bench --sync models
          the network: an injected per-dispatch latency, LABELED
          "injected_rtt_ms" (default 70 ms on cpu-fallback — the
          reference e2e benchmark's BitTwister figure — 0 on real
          hardware, env CELESTIA_BENCH_MESH_RTT_MS); the uninjected
          ratio is also reported ("vs_per_block_raw").
      {"metric": "mesh_scaling_blocks_per_sec", ...}  device-count
          scaling curve of the same batched dispatch (1, 2, 4, ...
          devices; virtual CPU devices on the fallback).

    Honors the fail-fast relay conventions: pure-CPU runs are labeled
    "backend": "cpu-fallback" (FORMATS §12.2); sizes/batch via
    CELESTIA_BENCH_MESH_K / CELESTIA_BENCH_MESH_BATCH.
    """
    import jax

    from celestia_app_tpu.da import edscache
    from celestia_app_tpu.parallel import mesh as mesh_mod
    from celestia_app_tpu.parallel import mesh_engine, streaming
    from celestia_app_tpu.utils import telemetry

    devices = jax.devices()
    platform = devices[0].platform
    backend = "cpu-fallback" if platform == "cpu" else platform
    target_k = 256
    k = int(os.environ.get(
        "CELESTIA_BENCH_MESH_K", "256" if platform == "tpu" else "32"))
    batch = int(os.environ.get("CELESTIA_BENCH_MESH_BATCH", "8"))
    reps = int(os.environ.get("CELESTIA_BENCH_MESH_REPS", "3"))

    def _ods(seed: int) -> np.ndarray:
        o = np.random.default_rng(seed).integers(
            0, 256, size=(k, k, 512), dtype=np.uint8)
        o[..., :29] = 0
        o[..., 28] = 7
        return o

    def counters():
        return telemetry.snapshot().get("counters", {})

    def delta(c0, c1, key):
        return c1.get(key, 0) - c0.get(key, 0)

    # -- 1. extend+commit through the mesh engine ------------------------
    ods = _ods(0)
    edscache.compute_entry(ods, "mesh")  # compile + warm
    best = None
    for _ in range(reps):
        t0 = time.perf_counter()
        entry = edscache.compute_entry(ods, "mesh")
        dt = (time.perf_counter() - t0) * 1e3
        best = dt if best is None else min(best, dt)
    mesh = mesh_engine.mesh_for(k)
    print(json.dumps({
        "metric": ("extend_commit_256_ms" if k == target_k
                   else f"extend_commit_{k}_ms"),
        "value": round(best, 3),
        "unit": "ms",
        "k": k,
        "target_k": target_k,
        "at_target_k": k == target_k,
        "devices": len(devices),
        "mesh": dict(mesh.shape) if mesh is not None else None,
        "residency": entry.residency(),
        "backend": backend,
    }), flush=True)

    # -- 2. batched multi-block dispatch vs the per-block pipeline -------
    # its own square size: the dispatch-boundary effect needs per-block
    # compute small enough that the boundary is visible at all on one
    # core (k=8 on the fallback); real hardware measures the target size
    bk = int(os.environ.get(
        "CELESTIA_BENCH_MESH_BATCH_K",
        str(target_k) if platform == "tpu" else "8"))
    rtt_s = float(os.environ.get(
        "CELESTIA_BENCH_MESH_RTT_MS",
        "0" if platform == "tpu" else "70")) / 1e3

    def _ods_b(seed: int) -> np.ndarray:
        o = np.random.default_rng(seed).integers(
            0, 256, size=(bk, bk, 512), dtype=np.uint8)
        o[..., :29] = 0
        o[..., 28] = 7
        return o

    odses = [_ods_b(100 + i) for i in range(batch)]
    stack_b = np.stack(odses)
    # warm both paths' compiles out of the clock. The batched path uses
    # the engine-selection rules of the produce path itself: the mesh's
    # sharded pipeline when active for k (always on real multi-chip at
    # k>=256), the single-chip vmapped program otherwise — metric 3
    # isolates the mesh's own scaling.
    edscache.compute_entry(odses[0], "device")
    mesh_engine.compute_entries_batched(stack_b)

    # per-block production pipeline: one dispatch AND one full-EDS host
    # fetch per block (what the single-device compute_entry pays today —
    # the host-boundary cost ROADMAP item 4 names). Prover warm runs on
    # the background warmer thread in BOTH paths and is not clocked.
    def _measure(rtt: float):
        best_pb = best_b = None
        for _ in range(reps):
            t0 = time.perf_counter()
            for o in odses:
                edscache.compute_entry(o, "device")  # dispatch + fetch
                if rtt:
                    time.sleep(rtt)  # one boundary round-trip PER BLOCK
            dt = time.perf_counter() - t0
            best_pb = dt if best_pb is None else min(best_pb, dt)
        for _ in range(reps):
            t0 = time.perf_counter()
            mesh_engine.compute_entries_batched(stack_b)
            if rtt:
                time.sleep(rtt)  # one round-trip for the WHOLE batch
            dt = time.perf_counter() - t0
            best_b = dt if best_b is None else min(best_b, dt)
        return batch / best_pb, batch / best_b

    c0 = counters()
    raw_pb, raw_b = _measure(0.0)
    c1 = counters()
    if rtt_s:
        per_block_bps, batched_bps = _measure(rtt_s)
    else:
        per_block_bps, batched_bps = raw_pb, raw_b
    # crossings measured over the uninjected pass: reps batched runs +
    # reps*batch per-block runs; only the batched runs' entries are
    # device-resident, and nothing samples them, so the delta must be 0
    crossings = delta(c0, c1, "edscache.host_crossings") / (reps * batch)
    print(json.dumps({
        "metric": "blocks_per_sec_batched",
        "value": round(batched_bps, 3),
        "unit": "blocks/s",
        "k": bk,
        "batch": batch,
        "per_block_blocks_per_sec": round(per_block_bps, 3),
        "vs_per_block": round(batched_bps / max(per_block_bps, 1e-9), 2),
        "vs_per_block_raw": round(raw_b / max(raw_pb, 1e-9), 2),
        "injected_rtt_ms": rtt_s * 1e3,
        "host_crossings_per_block": round(crossings, 4),
        "extend_runs_per_block": round(
            delta(c0, c1, "da.extend_runs") / (2 * reps * batch), 3),
        "backend": backend,
    }), flush=True)

    # -- 3. device-count scaling curve -----------------------------------
    stack = np.stack([_ods(100 + i) for i in range(batch)])
    curve = []
    d = 1
    while d <= len(devices):
        if d == 1:
            from celestia_app_tpu.da import eds as eds_mod

            run = eds_mod.jitted_pipeline_batched(k)
        else:
            from celestia_app_tpu.parallel import sharded_eds

            run = sharded_eds.jitted_sharded_pipeline(
                mesh_mod.make_mesh(d, k=k, devices=devices[:d]), k)
        np.asarray(run(stack)[3])  # compile + warm (fetch, not b_u_r)
        best_d = None
        for _ in range(reps):
            t0 = time.perf_counter()
            np.asarray(run(stack)[3])
            dt = time.perf_counter() - t0
            best_d = dt if best_d is None else min(best_d, dt)
        curve.append({"devices": d,
                      "blocks_per_sec": round(batch / best_d, 3)})
        d *= 2
    print(json.dumps({
        "metric": "mesh_scaling_blocks_per_sec",
        "value": curve[-1]["blocks_per_sec"],
        "unit": "blocks/s",
        "k": k,
        "batch": batch,
        "scaling": curve,
        "backend": backend,
    }), flush=True)


def measure_scenario() -> None:
    """Scenario-plane bench (--scenario). One BENCH JSON line per
    (scenario, scheme) cell of the matrix — scheme ranging over EVERY
    registered wire id (rs2d-nmt, cmt-ldpc, pcmt-polar), so a new codec
    is judged under the identical seeded attacks by registration alone.
    Each line is the scenario's verdict (FORMATS §19.2):
    blocks_to_detection, liveness_gap_s, false_condemnation_rate,
    recovery_s, plus the event-trace digest — the determinism witness
    (same seed reprints identical lines).

    The matrix: honest (the zero-false-condemnation control),
    withholding at each scheme's recoverability threshold, committed
    incorrect coding escalated to a verified fraud proof, and a
    partition-heal churn — per scheme, all on one seeded virtual
    timeline per cell. Pure host/CPU work (consensus + sampling +
    repair at small k): no relay involvement, no backend probe.

    The network-scale cells (ISSUE 18): `long-soak` (resource-churn
    soak under seeded PFB traffic + asymmetric per-message faults) and
    `fleet-scale` (1000+ continuation-driven lights over 1000+ virtual
    blocks, run TWICE per seed — the line carries
    verdict_bytes_identical) run on rs2d-nmt only: their subject is the
    scenario plane's scale and determinism, not the codec matrix.

    Knobs: CELESTIA_BENCH_SCENARIO_{VALIDATORS,LIGHTS,HEIGHTS,SEED},
    CELESTIA_BENCH_SCENARIOS (comma list to sub-select), and
    CELESTIA_BENCH_FLEET_{LIGHTS,HEIGHTS} for the fleet-scale cell."""
    import tempfile

    from celestia_app_tpu.sim import run_scenario, scenario_spec
    from celestia_app_tpu.sim.scenarios import verdict_bytes

    n_val = int(os.environ.get("CELESTIA_BENCH_SCENARIO_VALIDATORS", "8"))
    n_light = int(os.environ.get("CELESTIA_BENCH_SCENARIO_LIGHTS", "64"))
    heights = int(os.environ.get("CELESTIA_BENCH_SCENARIO_HEIGHTS", "5"))
    seed = int(os.environ.get("CELESTIA_BENCH_SCENARIO_SEED", "0"))
    fleet_lights = int(os.environ.get("CELESTIA_BENCH_FLEET_LIGHTS",
                                      "1000"))
    fleet_heights = int(os.environ.get("CELESTIA_BENCH_FLEET_HEIGHTS",
                                       "1000"))
    names = [s for s in os.environ.get(
        "CELESTIA_BENCH_SCENARIOS",
        "honest,withhold-threshold,incorrect-coding,partition-churn,"
        "long-soak,fleet-scale",
    ).split(",") if s]
    from celestia_app_tpu.da import codec as dacodec

    schemes = [dacodec.by_id(i).name for i in dacodec.registered_ids()]
    # the network-scale cells benchmark the scenario plane itself
    # (continuation fleet scale, soak churn, verdict determinism), not
    # the codec matrix — one scheme carries the claim
    single_scheme = {"long-soak", "fleet-scale"}
    for scenario in names:
        for scheme in (["rs2d-nmt"] if scenario in single_scheme
                       else schemes):
            if scenario == "fleet-scale":
                doc = scenario_spec(scenario, scheme=scheme, seed=seed,
                                    light_nodes=fleet_lights,
                                    heights=fleet_heights)
            elif scenario == "long-soak":
                doc = scenario_spec(scenario, scheme=scheme, seed=seed)
            else:
                doc = scenario_spec(scenario, scheme=scheme, seed=seed,
                                    validators=n_val,
                                    light_nodes=n_light,
                                    heights=heights)
            t0 = time.perf_counter()
            v = run_scenario(doc, workdir=tempfile.mkdtemp(
                prefix=f"bench-sim-{scenario}-"))
            wall = time.perf_counter() - t0
            line = {
                "metric": "scenario_verdict",
                "scenario": scenario,
                "scheme": scheme,
                "seed": seed,
                "validators": v["validators"],
                "light_nodes": v["light_nodes"],
                "heights_committed": v["heights_committed"],
                "blocks_to_detection": v["blocks_to_detection"],
                "liveness_gap_s": v["liveness_gap_s"],
                "false_condemnation_rate": v["false_condemnation_rate"],
                "recovery_s": v["recovery_s"],
                "light_halts": v["light_halts"],
                "unavailable_reports": v["unavailable_reports"],
                "events": v["events"],
                "trace_digest": v["trace_digest"],
                "sim_lights": v["sim_lights"],
                "sim_virtual_blocks": v["sim_virtual_blocks"],
                "peak_rss_bytes": v["peak_rss_bytes"],
                "wall_s": round(wall, 3),
                "backend": "host",
            }
            # per-op verdict blocks, present when the scenario arms them
            for block in ("traffic", "spam", "soak", "asym_msgs"):
                if v.get(block):
                    line[block] = v[block]
            if scenario == "fleet-scale":
                # the determinism claim IS the benchmark: same seed,
                # second full run, byte-identical canonical verdict
                t0 = time.perf_counter()
                v2 = run_scenario(doc, workdir=tempfile.mkdtemp(
                    prefix=f"bench-sim-{scenario}-"))
                line["rerun_wall_s"] = round(time.perf_counter() - t0, 3)
                line["verdict_bytes_identical"] = (
                    verdict_bytes(v) == verdict_bytes(v2))
            print(json.dumps(line), flush=True)


MODES = {
    "block": (measure_block,
              "block_e2e_ms, blocks_per_sec, first_sample_after_commit_ms",
              "extend-once block lifecycle: e2e commit + first sample"),
    "proofs": (measure_proofs, "share_proofs_per_sec_128",
               "batched share-proof serving throughput at k=128"),
    "admission": (measure_admission,
                  "sig_verify_per_sec, mempool_ingest_txs_per_sec",
                  "batched on-device secp256k1 + two-phase tx admission"),
    "repair": (measure_repair, "repair_128_ms, befp_verify_ms",
               "decode plane: 1/4-erased EDS repair + BEFP verification"),
    "codec": (measure_codec,
              "encode_ms, proof_bytes_per_sample, "
              "samples_to_99_confidence, repair_ms, fraud_verify_ms "
              "(per registered scheme) + rs_tunable_sweep",
              "DA commitment schemes head to head: 2D-RS+NMT vs CMT "
              "vs polar PCMT, plus the tunable-rate RS sweep"),
    "mempool": (measure_mempool,
                "mempool_ingest_txs_per_sec, mempool_reap_ms",
                "CAT pool ingest + priority reap throughput"),
    "chaos": (measure_chaos, "crash_replay_ms, chaos_heal_recovery_s",
              "fault plane: WAL crash replay + partition-heal liveness"),
    "scenario": (measure_scenario,
                 "scenario_verdict: blocks_to_detection, liveness_gap_s, "
                 "false_condemnation_rate, recovery_s, sim_lights, "
                 "sim_virtual_blocks, peak_rss_bytes (per scenario x "
                 "registered scheme: rs2d-nmt, cmt-ldpc, pcmt-polar) + "
                 "the long-soak and fleet-scale network cells",
                 "scenario plane: seeded virtual-time adversarial matrix "
                 "over the validator + light-node fleet, judged on "
                 "every registered wire id under identical seeds, plus "
                 "1000-light fleet determinism and long-horizon soak"),
    "sync": (measure_sync,
             "state_sync_join_s, blocksync_blocks_per_sec, "
             "snapshot_serve_ms",
             "sync plane: chunked state-sync join vs full replay"),
    "txsim": (measure_txsim,
              "blobs_per_sec, admission_commit_p99_ms, "
              "commitment_validate_per_sec",
              "traffic plane: sustained confirm-polled blob load at a "
              "live devnet + cached vs cold admission commitment "
              "validation"),
    "serve": (measure_serve,
              "samples_served_per_sec, sampler_round_trips_per_height, "
              "p99_sample_ms, pack_hit_ratio",
              "serving plane: pack-served vs live sampling under "
              "thousand-sampler load"),
    "read": (measure_read,
             "namespace_queries_per_sec, batched_vs_single_ratio, "
             "pack_vs_live_ratio, p99 per mode",
             "read plane: batched vs per-request namespace resolution "
             "+ static blob packs under concurrent followers"),
    "analyze": (measure_analyze,
                "analyze_cold_wall_s, analyze_warm_wall_s, "
                "analyze_effects_cold_wall_s, analyze_effects_warm_wall_s",
                "full-tree static analysis (call-graph taint + effect "
                "system) cold vs incremental-cache warm"),
    "obs": (measure_obs, "obs_overhead_pct",
            "observability overhead on the produce-block path"),
    "slo": (measure_slo,
            "slo_verdict_pass (+ deterministic verdict-bytes check)",
            "fleet-wide SLO verdict engine (tools/fleetmon.py) judged "
            "against a live, then quiesced, 2-validator HTTP devnet"),
    "compare": (run_compare,
                "per-metric trajectory across committed BENCH_*.json "
                "rounds; exit 2 on regression beyond tolerance",
                "bench history differ (tools/benchdiff.py): align "
                "rounds, flag regressions, CI-usable exit code"),
    "mesh": (measure_mesh,
             "extend_commit_256_ms, blocks_per_sec_batched, "
             "mesh_scaling_blocks_per_sec",
             "mesh plane: sharded extend+commit, multi-block batched "
             "dispatch with device-resident entries, device scaling"),
    "stream-mesh": (measure_stream_mesh,
                    "stream_mesh blocks/s (stderr+json)",
                    "multi-device sharded streaming pipeline"),
    "stream-batched": (_stream_batched, "stream_batched blocks/s",
                       "single-device batched block streaming"),
    "stream": (measure_stream, "stream blocks/s",
               "single-square streaming pipeline"),
    "stages": (measure_stages, "per-stage device timings (stderr)",
               "per-stage device timings of the extend+commit pipeline"),
    "measure-baseline": (_save_baseline,
                         "writes bench_baseline.json (cpu_ms, data_root)",
                         "record the native CPU baseline reference"),
}


if __name__ == "__main__":
    main()
